"""LocalCluster — vstart.sh analog: N mons + M OSDs in one process on
localhost sockets, with kill/revive for thrash tests (reference:
src/vstart.sh; qa/standalone/ceph-helpers.sh `run_mon`/`run_osd`/
`kill_daemons`; SURVEY.md §4 ring 2).

    with LocalCluster(n_mons=3, n_osds=6) as c:
        c.create_ec_pool("ecpool", k=4, m=2)
        io = c.client().open_ioctx("ecpool")
        io.write_full("x", b"...")
        c.kill_osd(3)
        io.read("x")          # degraded read
        c.revive_osd(3)       # delta recovery kicks in
"""
from __future__ import annotations

import socket
import sys
import time

from ..common.context import CephContext
from ..crush import CrushWrapper, build_hierarchical_map
from ..mon import MonMap, Monitor
from ..osd.daemon import OSD
from ..osd.osdmap import OSDMap
from ..client.rados import Rados


def _free_addrs(n: int) -> list[tuple[str, int]]:
    socks, addrs = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        addrs.append(("127.0.0.1", s.getsockname()[1]))
    for s in socks:
        s.close()
    return addrs


class LocalCluster:
    def __init__(
        self,
        n_mons: int = 3,
        n_osds: int = 6,
        hosts: int | None = None,
        conf_overrides: dict | None = None,
        with_mgr: bool = False,
        with_mds: bool = False,
        objectstore: str | None = None,
    ):
        """objectstore: None = in-memory stores handed across revives
        (fast, the round-2 behavior).  "kstore"/"bluestore" = PERSISTENT
        mode: each OSD mounts a store under a tmp data dir; kill_osd is
        a crash (no unmount) and revive_osd constructs a FRESH store
        from the same directory — real WAL replay + fsck on mount
        (reference: qa/standalone restarts daemons from disk)."""
        self.n_mons = n_mons
        self.n_osds = n_osds
        self.hosts = hosts or n_osds  # default: one OSD per host bucket
        self.conf_overrides = dict(conf_overrides or {})
        self.objectstore = objectstore
        self.data_dir: str | None = None
        if objectstore:
            import tempfile

            self.data_dir = tempfile.mkdtemp(prefix="ceph_tpu_osd_")
            self.conf_overrides.setdefault("objectstore", objectstore)
            self.conf_overrides.setdefault("osd_data", self.data_dir)
            self.conf_overrides.setdefault("osd_fsck_on_mount", True)
        self.with_mgr = with_mgr
        self.with_mds = with_mds
        self.mons: dict[str, Monitor] = {}
        self.osds: dict[int, OSD] = {}
        self.mgr = None
        self.mds = None
        self.rgw = None
        self.mon_addrs: list = []
        self._clients: list[Rados] = []
        self._rbd_mirrors: list = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "LocalCluster":
        addrs = _free_addrs(self.n_mons)
        self.mon_addrs = [list(a) for a in addrs]
        names = [chr(ord("a") + i) for i in range(self.n_mons)]
        monmap = MonMap({names[i]: addrs[i] for i in range(self.n_mons)})
        cmap = build_hierarchical_map(
            self.hosts, -(-self.n_osds // self.hosts)
        )
        initial = OSDMap(CrushWrapper(cmap), max_osd=self.n_osds)
        for nm in names:
            cct = self._cct(f"mon.{nm}")
            mon = Monitor(cct, nm, monmap, initial_osdmap=initial)
            self.mons[nm] = mon
            mon.start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not any(
            m.is_leader() for m in self.mons.values()
        ):
            time.sleep(0.05)
        if not any(m.is_leader() for m in self.mons.values()):
            raise TimeoutError("no mon leader")
        if self.with_mgr:
            from ..mgr import MgrDaemon

            self.mgr = MgrDaemon(self._cct("mgr"), self.mon_addrs)
            self.mgr.start()
            # daemons stream MMgrReport here (MgrMap-analog wiring)
            self.conf_overrides["mgr_addr"] = (
                f"{self.mgr.addr[0]}:{self.mgr.addr[1]}"
            )
        for i in range(self.n_osds):
            self._start_osd(i)
        # all OSDs booted: wait until every address is registered
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            m = self._leader().osdmon.osdmap
            if m is not None and len(m.osd_addrs) >= self.n_osds:
                break
            time.sleep(0.1)
        if self.with_mds:
            self.start_mds()
        return self

    def _cct(self, name: str) -> CephContext:
        # overrides go through the constructor: init-time features
        # (admin socket, lockdep) read conf DURING __init__, so setting
        # them afterwards would silently not take
        return CephContext(name, overrides=dict(self.conf_overrides))

    def _start_osd(self, i: int, store=None) -> OSD:
        osd = OSD(self._cct(f"osd.{i}"), i, self.mon_addrs, store=store)
        self.osds[i] = osd
        osd.start()
        return osd

    def _leader(self) -> Monitor:
        for m in self.mons.values():
            if m.is_leader():
                return m
        raise RuntimeError("no leader")

    @staticmethod
    def _stop_quietly(label: str, fn) -> None:
        """Best-effort teardown: one daemon dying mid-shutdown must not
        keep the rest of the cluster from stopping — but it must not
        vanish either (a repeatable shutdown crash is a real bug)."""
        try:
            fn()
        except Exception as e:
            print(f"# vstart: {label} shutdown raised: {e!r}",
                  file=sys.stderr)

    def stop(self) -> None:
        for d in self._rbd_mirrors:
            self._stop_quietly("rbd-mirror", d.stop)
        for c in self._clients:
            self._stop_quietly("client", c.shutdown)
            # the cluster minted this client's context (_cct), so the
            # cluster retires it — the Rados handle itself never owns
            # its cct (daemons embed Rados handles on shared contexts)
            self._stop_quietly("client cct", c.cct.shutdown)
        # gateways and the MDS are RADOS clients: stop them while OSDs are
        # still up so their shutdown I/O can reach the pools
        if self.rgw is not None:
            self._stop_quietly("rgw", self.rgw.shutdown)
        for rank, mds in sorted(getattr(self, "mds_ranks", {}).items()):
            if rank == 0:
                continue  # rank 0 is self.mds, handled below
            self._stop_quietly(f"mds.{rank}", mds.shutdown)
        if self.mds is not None:
            self._stop_quietly("mds.0", self.mds.shutdown)
        for i, osd in sorted(self.osds.items()):
            self._stop_quietly(f"osd.{i}", osd.shutdown)
        if self.mgr is not None:
            self._stop_quietly("mgr", self.mgr.shutdown)
        for mon in self.mons.values():
            self._stop_quietly(f"mon.{mon.name}", mon.shutdown)
        if self.data_dir is not None:
            import shutil

            shutil.rmtree(self.data_dir, ignore_errors=True)

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admin -------------------------------------------------------------
    def client(self, name: str = "client.admin") -> Rados:
        r = Rados(self._cct(name), self.mon_addrs, name=name)
        r.connect()
        self._clients.append(r)
        return r

    def mon_command(self, cmd: dict):
        c = self.client("client.vstart-admin")
        try:
            return c.command(cmd)
        finally:
            self._clients.remove(c)
            c.shutdown()
            c.cct.shutdown()

    def create_ec_pool(
        self, name: str, k: int = 4, m: int = 2, pg_num: int = 8,
        plugin: str = "jax", extra_profile: dict | None = None,
    ) -> None:
        prof = {
            "prefix": "osd erasure-code-profile set",
            "name": f"{name}_profile",
            "profile": {
                "plugin": plugin, "k": str(k), "m": str(m),
                "crush-failure-domain": "osd",
                **(extra_profile or {}),
            },
        }
        rv, res = self.mon_command(prof)
        assert rv == 0, (rv, res)
        rv, res = self.mon_command({
            "prefix": "osd pool create", "name": name, "pg_num": pg_num,
            "pool_type": "erasure", "erasure_code_profile": f"{name}_profile",
        })
        assert rv == 0, (rv, res)
        rv, res = self.mon_command({
            "prefix": "osd pool application enable",
            "pool": name, "app": "rados"})
        assert rv == 0, (rv, res)

    def create_replicated_pool(self, name: str, size: int = 3,
                               pg_num: int = 8,
                               min_size: int | None = None,
                               app: str = "rados") -> None:
        cmd = {
            "prefix": "osd pool create", "name": name, "pg_num": pg_num,
            "size": size,
        }
        if min_size is not None:
            cmd["min_size"] = min_size
        rv, res = self.mon_command(cmd)
        assert rv == 0, (rv, res)
        rv, res = self.mon_command({
            "prefix": "osd pool application enable",
            "pool": name, "app": app})
        assert rv == 0, (rv, res)

    def _ensure_replicated_pools(self, *names: str,
                                 app: str = "rados") -> None:
        """Create any of `names` that don't exist yet (service-pool
        bootstrap shared by the MDS and RGW starters)."""
        existing = {
            p.name for p in (self._leader().osdmon.osdmap.pools or {}).values()
        }
        for name in names:
            if name not in existing:
                self.create_replicated_pool(
                    name, size=min(3, self.n_osds), app=app)

    # -- filesystem (reference: vstart.sh's cephfs setup) ------------------
    def start_mds(self) -> None:
        """Create the FS pools (if absent) and start rank 0 (reference:
        `ceph fs new` + ceph-mds boot)."""
        from ..fs import MDSDaemon

        self._ensure_replicated_pools("cephfs_meta", "cephfs_data",
                                      app="cephfs")
        # restarts REBIND the previous address so surviving clients can
        # reach the new incarnation (the mon's MDSMap would republish it
        # upstream; here the addr is stable across failover instead)
        self.mds = MDSDaemon(self._cct("mds.0"), self.mon_addrs,
                             bind_addr=getattr(self, "_mds_addr", None))
        self.mds.start()
        self._mds_addr = self.mds.addr
        self.mds_ranks = getattr(self, "mds_ranks", {})
        self.mds_ranks[0] = self.mds

    def start_mds_rank(self, rank: int):
        """Start an additional ACTIVE rank (`max_mds` increase analog,
        round-4 verdict item #8).  Rank 0 must already be up."""
        from ..fs import MDSDaemon

        assert rank > 0 and self.mds is not None
        mds = MDSDaemon(self._cct(f"mds.{rank}"), self.mon_addrs,
                        rank=rank)
        mds.start()
        self.mds_ranks = getattr(self, "mds_ranks", {0: self.mds})
        self.mds_ranks[rank] = mds
        return mds

    def fail_mds_rank(self, rank: int) -> None:
        """Crash one active rank (no flush, beacon stops): the lowest
        surviving rank takes over its subtrees from the journal."""
        mds = self.mds_ranks.pop(rank)
        if mds is self.mds:
            self.mds = None
        mds.hard_kill()

    def kill_mds(self) -> None:
        """Hard-stop the MDS *without* the shutdown flush — the journal
        must carry the namespace (reference: MDS failover replay)."""
        if self.mds is not None:
            self.mds.hard_kill()
            getattr(self, "mds_ranks", {}).pop(0, None)
            self.mds = None

    def restart_mds(self) -> None:
        self.kill_mds()
        self.start_mds()

    def fs_client(self, name: str = "client.fs"):
        from ..fs import FSClient

        assert self.mds is not None and self.mds.addr is not None
        r = self.client(name)
        fs = FSClient(r.cct, r, self.mds.addr, name=name)
        fs.mount()
        return fs

    def start_rbd_mirror(self, src_pool: str, dst_pool: str,
                         interval: float = 0.2):
        """Start an rbd-mirror daemon replaying src_pool -> dst_pool
        (reference: the rbd-mirror process per pool peer)."""
        from ..client.rbd_mirror import MirrorDaemon

        cl = self.client("client.rbd-mirror")
        d = MirrorDaemon(cl.open_ioctx(src_pool), cl.open_ioctx(dst_pool),
                         interval=interval).start()
        self._rbd_mirrors.append(d)
        return d

    # -- object gateway (reference: radosgw) -------------------------------
    def start_rgw(self):
        """Create the rgw pools (if absent) and start the S3 gateway."""
        from ..rgw import RGWDaemon

        self._ensure_replicated_pools("rgw_meta", "rgw_data", app="rgw")
        self.rgw = RGWDaemon(self._cct("rgw.0"), self.mon_addrs)
        self.rgw.start()
        return self.rgw

    # -- fault injection ---------------------------------------------------
    def kill_osd(self, i: int) -> None:
        """Hard-stop an OSD (the thrasher's kill; reference:
        qa/tasks/thrashosds.py).  In-memory mode stashes the store
        object for revive; persistent mode CRASHES — no unmount, the
        store object is dropped and revive remounts from disk."""
        osd = self.osds.pop(i)
        if self.objectstore:
            osd.shutdown(umount=False)
            return
        self._stores = getattr(self, "_stores", {})
        self._stores[i] = osd.store
        osd.shutdown()

    def revive_osd(self, i: int) -> OSD:
        if self.objectstore:
            # fresh store from the same osd_data subdir: WAL replay +
            # fsck-on-mount happen inside the OSD boot
            return self._start_osd(i)
        store = getattr(self, "_stores", {}).pop(i, None)
        return self._start_osd(i, store=store)

    def mark_osd_down_out(self, i: int) -> None:
        """Push the map change without waiting for failure detection."""
        rv, res = self.mon_command({"prefix": "osd down", "id": i})
        assert rv == 0, (rv, res)
        rv, res = self.mon_command({"prefix": "osd out", "id": i})
        assert rv == 0, (rv, res)

    def mark_osd_in_up(self, i: int) -> None:
        rv, res = self.mon_command({"prefix": "osd in", "id": i})
        assert rv == 0, (rv, res)

    def wait_clean(self, pool: str, timeout: float = 30.0) -> None:
        """Wait until every shard of every PG of a pool reports the
        primary's version (recovery settled)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._all_clean(pool):
                return
            time.sleep(0.3)
        raise TimeoutError(f"pool {pool} not clean after {timeout}s")

    def _all_clean(self, pool_name: str) -> bool:
        leader = self._leader()
        m = leader.osdmon.osdmap
        if m is None:
            return False
        pid = next(
            (i for i, p in m.pools.items() if p.name == pool_name), None
        )
        if pid is None:
            return False
        pool = m.pools[pid]
        for ps in range(pool.pg_num):
            _up, _upp, acting, primary = m.pg_to_up_acting_osds(pid, ps)
            if self.osds.get(primary) is None:
                return False
            # every acting shard must agree on ONE version — `peer >=
            # primary` is not enough: a just-revived STALE primary (v1,
            # peers at v2) would read as clean in the window before its
            # pull-forward tick, and reads in that window serve old data
            vers = []
            for shard, o in enumerate(acting):
                if o < 0:
                    continue
                sosd = self.osds.get(o)
                if sosd is None:
                    return False
                spg = sosd.pgs.get(f"{pid}.{ps}")
                vers.append(spg.version if spg is not None else 0)
            if vers and any(v != vers[0] for v in vers):
                return False
            # content completeness: an acting-set permutation can leave a
            # version-current holder without its (new) shard role's
            # objects; versions alone cannot see that
            from ..osd.osdmap import PG_POOL_ERASURE

            is_ec = pool.type == PG_POOL_ERASURE
            posd = self.osds[primary]
            pshard = acting.index(primary) if is_ec else 0
            try:
                pobjs = {
                    obj for obj in posd.store.list_objects(
                        f"{pid}.{ps}s{pshard}")
                    if not obj.startswith("_")
                }
            except Exception:
                pobjs = set()
            for shard, o in enumerate(acting):
                if o < 0 or o == primary:
                    continue
                cid = f"{pid}.{ps}s{shard if is_ec else 0}"
                try:
                    sobjs = set(self.osds[o].store.list_objects(cid))
                except Exception:
                    sobjs = set()
                if pobjs - sobjs:
                    return False
        return True
