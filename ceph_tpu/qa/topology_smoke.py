"""cephtopo CI smoke: one encode path, three device topologies
(qa/ci_gate.sh step 12; ISSUE 16 acceptance).

The DevicePolicy refactor's whole claim is that topology is a *value*:
the same production encode (`parallel.sharded_apply_matrix` through
`make_mesh(policy=...)`) must produce bit-identical output whether the
policy grants

1. ``cpu``  — the 1-device CPU-fallback mesh (the laptop-test shape);
2. ``mesh`` — every device of the virtual 8-way host mesh (the
   multi-chip shape, conftest-style);
3. ``mesh`` with two devices pinned failed — the sentinel-degraded
   shape: the mesh SHRINKS to the 6 survivors instead of wedging, and
   the device-pool budget shrinks with it.

Every device/mesh decision in this smoke routes through DevicePolicy —
the smoke is itself CL9-clean, which is the point.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it next to the SARIF artifacts).
"""
from __future__ import annotations

import json
import os
import sys

from .smoke_util import wait_for as _wait

N_VIRTUAL = 8      # virtual host devices (matches tests/conftest.py)
PINNED_BAD = 2     # devices the degraded policy pins failed
K, M = 8, 4        # EC geometry
L = 3840           # stripe length: divisible by 1, 6, and 8


def main() -> int:
    # the virtual multi-device mesh must be requested before the first
    # backend init; this box's sitecustomize pins the tunneled TPU
    # backend and IGNORES the JAX_PLATFORMS env var, so config.update
    # is the reliable spelling for the cpu pin
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_VIRTUAL}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ..common.device_policy import DevicePolicy, reset_device_policy
    from ..gf import cauchy_good_coding_matrix
    from ..gf.reference_codec import encode_chunks
    from ..parallel import make_mesh, sharded_apply_matrix

    problems: list[str] = []
    summary: dict = {"smoke": "topology", "n_virtual": N_VIRTUAL}

    # a stray policy from an earlier in-process daemon must not leak in
    reset_device_policy()

    full = DevicePolicy("mesh")
    if not _wait(lambda: full.mesh_size() >= N_VIRTUAL, timeout=10):
        problems.append(
            f"virtual mesh never reached {N_VIRTUAL} devices "
            f"(got {full.mesh_size()}; XLA_FLAGS not honored?)")
        summary["problems"] = problems
        print(json.dumps(summary, indent=2, default=str))
        return 1

    # pin the LAST two granted rows failed — deterministic stand-in for
    # two sentinel probe failures (same "platform:id" row format)
    bad = tuple(f"{d.platform}:{d.id}" for d in full.devices()[-PINNED_BAD:])
    policies = {
        "cpu-1": DevicePolicy("cpu"),
        f"mesh-{N_VIRTUAL}": DevicePolicy("mesh"),
        "degraded": DevicePolicy("mesh", failed=bad),
    }
    summary["pinned_failed"] = list(bad)

    want_sizes = {
        "cpu-1": 1,
        f"mesh-{N_VIRTUAL}": N_VIRTUAL,
        "degraded": N_VIRTUAL - PINNED_BAD,
    }

    coding = cauchy_good_coding_matrix(K, M)
    data = np.random.default_rng(16).integers(
        0, 256, (K, L), dtype=np.uint8)
    reference = encode_chunks(coding, data)

    sizes: dict[str, int] = {}
    for name, pol in policies.items():
        mesh = make_mesh(policy=pol)
        sizes[name] = int(mesh.devices.size)
        if sizes[name] != want_sizes[name]:
            problems.append(
                f"{name}: mesh has {sizes[name]} devices, "
                f"want {want_sizes[name]}")
            continue
        got = np.asarray(sharded_apply_matrix(mesh, coding, data))
        if not np.array_equal(got, reference):
            problems.append(
                f"{name}: encode output diverged from the reference "
                f"({int((got != reference).sum())} of {got.size} bytes)")
    summary["mesh_sizes"] = sizes

    # the degraded mesh must actually exclude the pinned rows
    deg_rows = {f"{d.platform}:{d.id}"
                for d in policies["degraded"].devices()}
    if deg_rows & set(bad):
        problems.append(
            f"degraded policy still grants pinned-failed devices: "
            f"{sorted(deg_rows & set(bad))}")

    # and the pool budget shrinks with the mesh (per-device share x
    # live count), instead of survivors inheriting the dead chips' share
    max_bytes = 8 << 20
    full_budget = policies[f"mesh-{N_VIRTUAL}"].pool_budget(max_bytes)
    deg_budget = policies["degraded"].pool_budget(max_bytes)
    summary["pool_budget"] = {
        "configured": max_bytes, "full": full_budget, "degraded": deg_budget}
    if full_budget != max_bytes:
        problems.append(
            f"healthy mesh budget {full_budget} != configured {max_bytes}")
    want_deg = (max_bytes // N_VIRTUAL) * (N_VIRTUAL - PINNED_BAD)
    if deg_budget != want_deg:
        problems.append(
            f"degraded budget {deg_budget} != {want_deg} "
            f"(per-device share x {N_VIRTUAL - PINNED_BAD} survivors)")

    if not problems:
        summary["parity"] = "bit-identical across all topologies"
    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
