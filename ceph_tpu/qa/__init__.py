"""Ring-2 test infrastructure: in-process multi-daemon clusters
(reference: src/vstart.sh + qa/standalone/ceph-helpers.sh; SURVEY.md §4)."""
from .vstart import LocalCluster

__all__ = ["LocalCluster"]
