"""cephplace CI smoke: placement-plane observability end to end
(qa/ci_gate.sh step 11; ISSUE 15 acceptance).

Drives the WHOLE surface through the production path, no shortcuts:

1. a LocalCluster (mgr hosted, replicated pool) with the placement
   module scanning on demand; ``ceph_placement_*`` series must render
   on the prometheus exporter;
2. one OSD is marked out mid-life: the placement module's epoch diff
   must FORECAST the remap, and the forecast must match the observed
   acting-set churn (`pg dump` up sets before vs after — the scalar
   mapping path, an independent implementation) within tolerance;
3. a deterministic imbalance is stacked via pg-upmap-items with the
   balancer off: ``PG_IMBALANCE`` must raise in `health`/`status`;
4. the balancer is activated and run: it must commit moves, the
   exported score must improve (score_after <= score_before, strict
   when moves committed), and ``PG_IMBALANCE`` must clear once the
   deviation converges under the bound;
5. `balancer status` and `placement diff` must answer over the mon
   command path.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it next to the SARIF artifacts).
"""
from __future__ import annotations

import json
import sys
import time

POOL = "placesmoke"
PG_NUM = 16
#: forecast-vs-observed agreement bound: both sides derive from the
#: same map epochs (batched vs scalar paths), so disagreement beyond
#: rounding means one path is wrong
TOLERANCE = 0.10


from .smoke_util import gauge as _gauge, scrape as _scrape, wait_for as _wait


def _up_sets(c, pool_id: int) -> dict[str, set[int]]:
    """{pgid: up-set} from `pg dump` — the mon's SCALAR mapping path,
    independent of the batched scan under test."""
    rv, dump = c.mon_command({"prefix": "pg dump"})
    if rv != 0:
        return {}
    return {
        r["pgid"]: {int(o) for o in r["up"] if int(o) >= 0}
        for r in dump.get("pg_stats", [])
        if r["pgid"].startswith(f"{pool_id}.")
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..qa.vstart import LocalCluster

    problems: list[str] = []
    summary: dict = {}
    overrides = {
        "mgr_report_interval": 0.2,
        "mgr_digest_interval": 0.2,
        "mgr_placement_interval": 3600.0,   # scans driven by hand
        "mgr_balancer_interval": 3600.0,    # passes driven by hand
        "mgr_balancer_active": False,
    }
    with LocalCluster(n_mons=1, n_osds=4, with_mgr=True,
                      conf_overrides=overrides) as c:
        rv, res = c.mon_command({
            "prefix": "osd pool create", "name": POOL,
            "pg_num": PG_NUM, "size": 2,
        })
        if rv != 0:
            problems.append(f"pool create refused: {rv} {res}")
        pool_id = (res or {}).get("pool_id")
        c.mon_command({"prefix": "osd pool application enable",
                       "pool": POOL, "app": "rados"})
        io = c.client().open_ioctx(POOL)
        for i in range(8):
            io.write_full(f"ob{i}", bytes([i + 1]) * 4096)
        pm = c.mgr.module("placement")
        if not _wait(lambda: c.mgr.mc.osdmap is not None
                     and pool_id in c.mgr.mc.osdmap.pools, 15.0):
            problems.append("mgr never saw the pool")

        # -- 1. series render on the exporter --------------------------
        url = c.mgr.module("prometheus").url
        pm.scan()
        wanted = ("ceph_placement_pool_score",
                  "ceph_placement_osd_deviation",
                  "ceph_remap_epochs_diffed", "ceph_balancer_passes")
        if not _wait(lambda: all(m in _scrape(url) for m in wanted),
                     15.0):
            body = _scrape(url)
            problems.append("placement series never rendered: missing "
                            + ", ".join(m for m in wanted
                                        if m not in body))

        # -- 2. forecast vs observed churn on an osd-out ---------------
        pm.scan()  # prime the previous-epoch mapping cache
        before = _up_sets(c, pool_id)
        victim = 3
        rv, res = c.mon_command({"prefix": "osd out", "id": victim})
        if rv != 0:
            problems.append(f"osd out refused: {rv} {res}")
        if not _wait(lambda: not c.mgr.mc.osdmap.is_in(victim), 10.0):
            problems.append("mgr never saw the out epoch")
        out_epoch = c.mgr.mc.osdmap.epoch
        pm.scan()
        after = _up_sets(c, pool_id)
        observed_pgs = observed_shards = 0
        for pgid, b in after.items():
            new = b - before.get(pgid, set())
            if new:
                observed_pgs += 1
                observed_shards += len(new)

        # the mon serves `placement diff` from the mgr's PUSHED digest
        # (refreshed every mgr_digest_interval), so the forecast lands
        # asynchronously after the scan — poll until the digest carries
        # a diff covering the out epoch
        def _mon_diff():
            rv2, pd2 = c.mon_command({"prefix": "placement diff"})
            d2 = (pd2 or {}).get("diff") if rv2 == 0 else None
            if d2 and d2.get("to_epoch", 0) >= out_epoch:
                return d2
            return None

        box: dict = {}
        _wait(lambda: box.update(d=_mon_diff()) or box["d"], 10.0)
        diff = box.get("d")
        if not diff:
            rv, pd = c.mon_command({"prefix": "placement diff"})
            problems.append(f"`placement diff` carried no forecast for "
                            f"epoch >= {out_epoch}: {rv} {pd}")
        else:
            fc_pgs = diff.get("pgs_remapped", 0)
            fc_shards = diff.get("shards_remapped", 0)
            summary["forecast"] = {
                "pgs": fc_pgs, "shards": fc_shards,
                "misplaced_fraction": diff.get("misplaced_fraction"),
                "predicted_bytes": diff.get("predicted_bytes"),
            }
            summary["observed"] = {"pgs": observed_pgs,
                                   "shards": observed_shards}
            if observed_pgs == 0:
                problems.append("marking an OSD out remapped nothing "
                                "(scenario broken)")
            else:
                for what, fc, ob in (("pgs", fc_pgs, observed_pgs),
                                     ("shards", fc_shards,
                                      observed_shards)):
                    if abs(fc - ob) > max(1, TOLERANCE * ob):
                        problems.append(
                            f"forecast {what} {fc} vs observed {ob} "
                            f"beyond {TOLERANCE:.0%} tolerance")

        # -- 3. deterministic imbalance raises PG_IMBALANCE ------------
        m = c.mgr.mc.osdmap
        stacked = 0
        up0, _ = m.map_pool(pool_id)
        for ps in range(PG_NUM):
            row = [int(o) for o in up0[ps] if int(o) >= 0]
            if 0 in row or not row:
                continue
            rv, res = c.mon_command({
                "prefix": "osd pg-upmap-items", "pool": pool_id,
                "ps": ps, "mappings": [[row[-1], 0]],
            })
            if rv == 0:
                stacked += 1
        summary["stacked_upmaps"] = stacked
        if not stacked:
            problems.append("could not stack any upmap imbalance")
        if not _wait(lambda: len(c.mgr.mc.osdmap.pg_upmap_items)
                     >= stacked, 10.0):
            problems.append("mgr never saw the stacked upmaps")
        rep = pm.scan()
        d0 = rep["max_deviation"] if rep else 0.0
        summary["stacked_max_deviation"] = round(d0, 2)
        c.mgr.cct.conf.set("mgr_placement_max_deviation",
                           max(0.5, d0 - 1.0))
        pm.scan()

        def check_state():
            rv2, st = c.mon_command({"prefix": "status"})
            if rv2 != 0:
                return None
            return (st.get("health") or {}).get("checks") or {}

        if not _wait(lambda: "PG_IMBALANCE" in (check_state() or {}),
                     10.0):
            problems.append(
                f"PG_IMBALANCE never raised (max_deviation {d0})")

        # -- 4. balancer run improves the exported score, check clears -
        # the balancer refuses a degraded cluster (upstream parity), and
        # the out-osd + stacked-upmap remaps above leave objects
        # degraded until recovery lands them — settle first, as an
        # operator balancing a live cluster would
        try:
            c.wait_clean(POOL, timeout=30)
        except TimeoutError:
            problems.append("pool never settled after the stacked "
                            "upmaps; balancer phase would be refused")
        c.mgr.cct.conf.set("mgr_balancer_active", True)
        bal = c.mgr.module("balancer")
        bal.optimize_once()
        if (bal.status().get("last_skip") or {}).get("reason"):
            # lingering stale degraded rows can outlive wait_clean by a
            # report cycle — give the gate a moment and retry once
            _wait(lambda: bal.optimize_once() or bal.status()["passes"],
                  10.0)
        st = bal.status()
        lp = st.get("last_pass") or {}
        summary["balancer"] = {
            "proposed": lp.get("proposed"),
            "committed": lp.get("committed"),
            "failed": lp.get("failed"),
            "score_before": (lp.get("score_before") or {}).get("score"),
            "score_after": (lp.get("score_after") or {}).get("score"),
        }
        if not lp.get("committed"):
            problems.append(f"balancer committed no moves against a "
                            f"stacked imbalance: {lp}")
        if st.get("balancer_errors"):
            problems.append(f"balancer commit errors: "
                            f"{st.get('last_error')}")
        sb = (lp.get("score_before") or {}).get("score", 0.0)
        sa = (lp.get("score_after") or {}).get("score", 0.0)
        if sa > sb or (lp.get("committed") and not sa < sb):
            problems.append(
                f"balancer pass did not improve the score: "
                f"{sb} -> {sa}")
        # the exported gauges must carry the same story
        if not _wait(lambda: (_gauge(_scrape(url),
                                     "ceph_balancer_moves_committed")
                              or 0) > 0, 10.0):
            problems.append("ceph_balancer_moves_committed never "
                            "rendered > 0")
        body = _scrape(url)
        exp_b = _gauge(body, "ceph_balancer_score_before")
        exp_a = _gauge(body, "ceph_balancer_score_after")
        summary["exported_scores"] = {"before": exp_b, "after": exp_a}
        if exp_b is None or exp_a is None or exp_a > exp_b:
            problems.append(f"exported balancer scores wrong: "
                            f"{exp_b} -> {exp_a}")
        # wait for the committed upmaps to land, rescan, clear the check
        if not _wait(lambda: pm.scan() is not None
                     and pm.snapshot()["cluster"]["max_deviation"] < d0,
                     15.0):
            problems.append("deviation never improved after the "
                            "balancer pass")
        d1 = pm.snapshot()["cluster"]["max_deviation"]
        summary["balanced_max_deviation"] = round(d1, 2)
        c.mgr.cct.conf.set("mgr_placement_max_deviation", d1 + 0.5)
        pm.scan()
        if not _wait(lambda: "PG_IMBALANCE" not in (check_state() or {}),
                     10.0):
            problems.append("PG_IMBALANCE never cleared after "
                            "convergence")

        # -- 5. balancer status over the mon path ----------------------
        rv, bs = c.mon_command({"prefix": "balancer status"})
        if rv != 0 or not bs.get("passes"):
            problems.append(f"`balancer status` broken: {rv} {bs}")
        else:
            summary["balancer_status_passes"] = bs["passes"]

    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
