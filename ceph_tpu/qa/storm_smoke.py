"""cephstorm CI smoke: a seeded 250-stub failure storm end to end
(qa/ci_gate.sh step 14; ISSUE 18 acceptance).

One process, no shortcuts on the control plane:

1. a :class:`~ceph_tpu.qa.storm.StormCluster` — 250 stub OSDs across 4
   racks under a REAL monitor + mgr (every kill/revive/reweight is a
   committed paxos proposal; health checks come from the real digest
   pipeline);
2. a seeded :class:`~ceph_tpu.qa.storm.StormPlanner` storm — kill and
   revive waves (single OSDs and whole racks), a recv-drop rack
   netsplit, reweight remap churn, all under 2-tenant traffic from
   ``bench/traffic.py``'s generators;
3. quiesce, then EVERY :class:`StormInvariantChecker` gate: no acked
   write lost, all PGs clean, forecast-vs-observed remap churn within
   10%, bounded controller oscillation, QoS class conservation, health
   raise-and-clear symmetry, and bit-identical replay (same seed =>
   same event log + ``plan_digest``);
4. a bare-map remap storm (:func:`run_remap_storm`) cross-checking the
   batched mapper against the scalar reference on a PG sample.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it as ``storm_smoke.json``).
"""
from __future__ import annotations

import json
import sys
import time

SEED = 18
N_STUBS = 250
RACKS = 4
EVENTS = 160
PG_NUM = 32
POOL = "stormdata"


def main() -> int:
    from .storm import (
        StormCluster,
        StormInvariantChecker,
        StormPlanner,
        run_remap_storm,
    )

    problems: list[str] = []
    summary: dict = {"seed": SEED, "n_stubs": N_STUBS, "events": EVENTS}
    t0 = time.monotonic()
    try:
        with StormCluster(n_stubs=N_STUBS, n_mons=1, racks=RACKS) as c:
            c.create_pool(POOL, size=3, pg_num=PG_NUM, min_size=2)
            planner = StormPlanner(cluster=c, seed=SEED, n_tenants=2,
                                   pool=POOL)
            planner.run(EVENTS)
            planner.quiesce()
            summary["metadata"] = planner.metadata()
            checker = StormInvariantChecker(c, planner)
            try:
                summary["invariants"] = checker.check()
            except AssertionError as e:
                problems.append(f"invariant violation: {e}")
            if not c.acked:
                problems.append("storm acked no writes — traffic never "
                                "reached min_size, nothing was checked")
            if not c.remap["events"]:
                problems.append("storm committed no map changes — no "
                                "remap churn was forecast")
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        problems.append(f"storm crashed: {type(e).__name__}: {e}")
    try:
        summary["remap_storm"] = run_remap_storm(
            n_osds=128, pg_num=2048, seed=SEED, rounds=3, sample=64)
    except AssertionError as e:
        problems.append(f"remap storm drift: {e}")
    except Exception as e:  # noqa: BLE001
        problems.append(f"remap storm crashed: {type(e).__name__}: {e}")
    summary["elapsed_s"] = round(time.monotonic() - t0, 1)
    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
