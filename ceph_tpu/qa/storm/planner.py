"""StormPlanner — pure seeded storm plans, thrasher discipline at scale.

``plan()`` is a pure function of the constructor parameters: the same
(seed, shape) always yields the same event list and the same
``plan_digest()``.  The planner mirrors cluster state (dead stubs,
armed splits, weights) WHILE drawing so eligibility filters never
depend on execution — which is what makes replay exact: the checker
re-plans with the same seed and asserts event-for-event equality.

Event vocabulary (fixed draw order — reordering ``_KINDS`` changes
digests, so treat it as part of the wire format):

* ``("write", pool, oid, size, client_key)`` / ``("read", pool, oid)``
  — tenant traffic from :mod:`ceph_tpu.bench.traffic`'s
  ``tenant_next_op`` (RGW S3 / CephFS metadata / RBD snapshot mixes,
  bursty/diurnal arrival, hot-object populations), one
  ``derive_rng(seed, "tenant", i)`` stream per tenant.
* ``("idle",)`` — a thinned arrival draw; kept in the plan so plan
  length and digests are deterministic.
* ``("tick", dt)`` — advance sim time, drain schedulers, feed the mgr.
* ``("kill", osd)`` / ``("revive", osd)`` — single-OSD churn.
* ``("kill_rack", r)`` / ``("revive_rack", r)`` — cascading failure.
* ``("netsplit", a, b)`` / ``("heal", a, b)`` — recv-drop rack splits.
* ``("reweight", osd, w)`` — remap churn without failures.
* ``("mon_churn", name)`` — force a re-election on one monitor.
"""
from __future__ import annotations

import hashlib
import random

from ...bench.traffic import (
    DEFAULT_SEED,
    TENANT_KINDS,
    derive_rng,
    tenant_next_op,
    tenant_objects,
)

# (kind, weight) in FIXED order — the draw distribution is part of the
# plan's identity, exactly the thrasher's _KINDS discipline.
_KINDS = (
    ("op", 10),
    ("tick", 6),
    ("kill", 3),
    ("revive", 3),
    ("kill_rack", 1),
    ("revive_rack", 1),
    ("netsplit", 2),
    ("heal", 2),
    ("reweight", 2),
    ("mon_churn", 1),
)


class StormPlanner:
    def __init__(self, cluster=None, seed: int = DEFAULT_SEED,
                 n_stubs: int | None = None, n_mons: int | None = None,
                 racks: int | None = None,
                 osds_per_host: int | None = None,
                 pool: str = "stormdata",
                 n_tenants: int = 4, objects_per_tenant: int = 64,
                 max_dead_frac: float = 0.3, max_splits: int = 2):
        self.cluster = cluster
        self.seed = int(seed)
        self.n_stubs = n_stubs if n_stubs is not None else cluster.n_stubs
        self.n_mons = n_mons if n_mons is not None else cluster.n_mons
        self.racks = racks if racks is not None else cluster.racks
        self.osds_per_host = (osds_per_host if osds_per_host is not None
                              else cluster.osds_per_host)
        self.pool = pool
        self.n_tenants = n_tenants
        self.objects_per_tenant = objects_per_tenant
        self.max_dead_frac = max_dead_frac
        self.max_splits = max_splits
        self.events: list[tuple] = []
        #: executed-event log (run()) — the replay-equality artifact
        self.executed: list[tuple] = []

    # -- topology mirror (must agree with StormCluster.start) --------------
    def rack_of(self, osd: int) -> int:
        hosts = -(-self.n_stubs // self.osds_per_host)
        per = max(1, hosts // self.racks)
        return min((osd // self.osds_per_host) // per, self.racks - 1)

    # -- pure planning ------------------------------------------------------
    def plan(self, n_events: int) -> list[tuple]:
        rng = random.Random(self.seed)
        kinds = [k for k, _w in _KINDS]
        weights = [w for _k, w in _KINDS]
        tenants = []
        for i in range(self.n_tenants):
            kind = TENANT_KINDS[i % len(TENANT_KINDS)]
            name = f"tenant{i}"
            tenants.append({
                "name": name, "kind": kind,
                "objects": tenant_objects(kind, name,
                                          self.objects_per_tenant),
                "rng": derive_rng(self.seed, "tenant", i),
            })
        # state mirror the eligibility filters run against
        dead: set[int] = set()
        splits: set[tuple[int, int]] = set()
        weights_by_osd: dict[int, float] = {}
        max_dead = int(self.max_dead_frac * self.n_stubs)
        by_rack: dict[int, list[int]] = {}
        for o in range(self.n_stubs):
            by_rack.setdefault(self.rack_of(o), []).append(o)

        events: list[tuple] = []
        t = tenants[0]
        first = tenant_next_op(t["kind"], t["rng"], t["objects"],
                               t_frac=0.0)
        if first is None or first[0] != "write":
            first = ("write", t["objects"][0],
                     {"s3": 8192, "fs": 512, "rbd": 4096}[t["kind"]])
        events.append(("write", self.pool, first[1], first[2],
                       f"{t['name']}/{self.pool}"))
        while len(events) < n_events:
            t_frac = len(events) / max(1, n_events)
            kind = rng.choices(kinds, weights=weights)[0]
            if kind == "op":
                t = tenants[rng.randrange(len(tenants))]
                got = tenant_next_op(t["kind"], t["rng"], t["objects"],
                                     t_frac=t_frac)
                if got is None:
                    events.append(("idle",))
                else:
                    op, oid, size = got
                    if op == "write":
                        events.append(("write", self.pool, oid, size,
                                       f"{t['name']}/{self.pool}"))
                    else:
                        events.append(("read", self.pool, oid))
            elif kind == "tick":
                events.append(("tick", round(0.1 + 0.4 * rng.random(), 3)))
            elif kind == "kill":
                alive = [o for o in range(self.n_stubs) if o not in dead]
                if len(dead) >= max_dead or not alive:
                    continue
                o = rng.choice(alive)
                dead.add(o)
                events.append(("kill", o))
            elif kind == "revive":
                if not dead:
                    continue
                o = rng.choice(sorted(dead))
                dead.discard(o)
                events.append(("revive", o))
            elif kind == "kill_rack":
                cands = [r for r, osds in sorted(by_rack.items())
                         if any(o not in dead for o in osds)
                         and len(dead | set(osds)) <= max_dead]
                if not cands:
                    continue
                r = rng.choice(cands)
                dead |= set(by_rack[r])
                events.append(("kill_rack", r))
            elif kind == "revive_rack":
                cands = [r for r, osds in sorted(by_rack.items())
                         if any(o in dead for o in osds)]
                if not cands:
                    continue
                r = rng.choice(cands)
                dead -= set(by_rack[r])
                events.append(("revive_rack", r))
            elif kind == "netsplit":
                if self.racks < 2 or len(splits) >= self.max_splits:
                    continue
                pairs = [(a, b) for a in range(self.racks)
                         for b in range(a + 1, self.racks)
                         if (a, b) not in splits]
                if not pairs:
                    continue
                pair = rng.choice(pairs)
                splits.add(pair)
                events.append(("netsplit",) + pair)
            elif kind == "heal":
                if not splits:
                    continue
                pair = rng.choice(sorted(splits))
                splits.discard(pair)
                events.append(("heal",) + pair)
            elif kind == "reweight":
                o = rng.randrange(self.n_stubs)
                w = rng.choice((0.5, 1.0))
                if weights_by_osd.get(o, 1.0) == w:
                    continue
                weights_by_osd[o] = w
                events.append(("reweight", o, w))
            elif kind == "mon_churn":
                if self.n_mons < 2:
                    continue
                events.append(("mon_churn",
                               chr(ord("a") + rng.randrange(self.n_mons))))
        self.events = events  # noqa: CL11 — the replay artifact run()/metadata() read; plan() output itself is pure
        return events

    def plan_digest(self, events: list[tuple] | None = None) -> str:
        h = hashlib.sha256()
        for ev in (events if events is not None else self.events):
            h.update(repr(ev).encode())
        return h.hexdigest()[:16]

    # -- execution ----------------------------------------------------------
    def run(self, n_events: int = 200) -> list[tuple]:
        """Plan (if not already planned to this length) and execute
        against the cluster; returns the executed-event log."""
        if len(self.events) != n_events:
            self.plan(n_events)
        c = self.cluster
        assert c is not None, "run() needs a cluster"
        for ev in self.events:
            kind = ev[0]
            if kind == "write":
                c.write(ev[1], ev[2], ev[3], client_key=ev[4])
            elif kind == "read":
                c.read(ev[1], ev[2])
            elif kind == "idle":
                pass
            elif kind == "tick":
                c.tick(ev[1])
            elif kind == "kill":
                c.kill_stub(ev[1])
            elif kind == "revive":
                c.revive_stub(ev[1])
            elif kind == "kill_rack":
                c.kill_rack(ev[1])
            elif kind == "revive_rack":
                c.revive_rack(ev[1])
            elif kind == "netsplit":
                c.split_racks(ev[1], ev[2])
            elif kind == "heal":
                c.heal_racks(ev[1], ev[2])
            elif kind == "reweight":
                c.reweight(ev[1], ev[2])
            elif kind == "mon_churn":
                c.mon_churn(ev[1])
            else:  # pragma: no cover — vocabulary is closed above
                raise ValueError(f"unknown storm event {ev!r}")
            self.executed.append(ev)
        return self.executed

    def quiesce(self, timeout: float = 60.0) -> None:
        self.cluster.quiesce(timeout=timeout)

    def metadata(self) -> dict:
        """Run metadata for artifacts — seed + digest is the replay
        contract (same seed, same shape => same storm)."""
        return {
            "seed": self.seed,
            "n_stubs": self.n_stubs,
            "n_mons": self.n_mons,
            "racks": self.racks,
            "n_tenants": self.n_tenants,
            "events": len(self.events),
            "plan_digest": self.plan_digest(),
        }
