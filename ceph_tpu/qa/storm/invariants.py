"""Storm invariant gates — what a failure storm must NEVER break.

:class:`StormInvariantChecker` runs after ``quiesce()`` and raises
``AssertionError`` with a storm-replay recipe (seed + plan digest) on
the first violation:

1. **No acked-write loss** — every write the cluster acked reads back
   at a version >= the acked version, with the payload integral for
   whatever version is returned (a newer unacked write superseding an
   acked one is legal; silent loss or corruption is not).
2. **All PGs clean** — after quiesce + recovery, every acting shard of
   every PG holds identical object/version sets; nothing degraded.
3. **Forecast vs observed churn** — the batched
   :func:`~ceph_tpu.osd.placement.diff_mappings` forecast accumulated
   across every map change agrees with the scalar observed shard churn
   within 10%.
4. **Bounded controller oscillation** — the closed QoS loop (pure
   :class:`~ceph_tpu.mgr.qos_module.QoSController` against a linear
   queue model) stops flip-flopping once settled; the pre-hysteresis
   limit cycle (``queue_p99_recover_frac=1.0``) is the regression this
   gate pins.
5. **QoS class conservation** — per stub, every enqueued op is
   accounted: served by a live class, still queued, or folded into
   the retirement aggregate; dynamic class count never exceeds the cap.
6. **Health raise-and-clear symmetry** — every check the storm raised
   is clear after quiesce.
7. **Replay determinism** — re-planning the same seed on a detached
   planner reproduces the event list and the plan digest bit-for-bit.
"""
from __future__ import annotations

import random

import numpy as np

from ...crush import CrushWrapper, build_hierarchical_map
from ...mgr.qos_module import QoSClamps, QoSController, QoSObservation
from ...osd.osdmap import OSDMap
from ...osd.placement import diff_mappings
from .planner import StormPlanner

#: forecast-vs-observed agreement: |fc - ob| <= max(floor, FRAC * ob)
CHURN_TOLERANCE = 0.10
CHURN_FLOOR = 8


def controller_flip_count(recover_frac: float = 0.8, steps: int = 60,
                          gain: float = 3.5, op_rate: float = 2000.0,
                          max_stripes: int = 64) -> int:
    """Drive the pure controller closed-loop against a linear queue
    model (p99 = gain * window) and count window direction flips in the
    settled second half.  ``recover_frac=1.0`` — back off above target
    but regrow the moment p99 dips under it — reproduces the limit
    cycle the hysteresis band removes; 0.8 settles to zero flips."""
    ctrl = QoSController(QoSClamps(queue_p99_recover_frac=recover_frac))
    window, last_delta, flips = 4.0, 0.0, 0
    for step in range(steps):
        obs = QoSObservation(window_ms=window, max_stripes=max_stripes,
                             queue_p99_ms=gain * window,
                             op_rate=op_rate)
        new = ctrl.plan(obs)["window_ms"]
        delta = new - window
        if step >= steps // 2 and delta * last_delta < 0:
            flips += 1
        if abs(delta) > 1e-3:
            last_delta = delta
        window = new
    return flips


class StormInvariantChecker:
    def __init__(self, cluster, planner: StormPlanner):
        self.cluster = cluster
        self.planner = planner

    def _recipe(self) -> str:
        md = self.planner.metadata()
        return (f"replay: seed={md['seed']} n_stubs={md['n_stubs']} "
                f"digest={md['plan_digest']}")

    def check(self) -> dict:
        report = {"recipe": self.planner.metadata()}
        report["acked_writes"] = self.check_no_acked_write_loss()
        report["pgs"] = self.check_pgs_clean()
        report["remap"] = self.check_forecast_vs_observed()
        report["controller_flips"] = self.check_controller_oscillation()
        report["qos"] = self.check_class_conservation()
        report["health"] = self.check_health_symmetry()
        report["replay"] = self.check_replay_determinism()
        return report

    # 1 ---------------------------------------------------------------------
    def check_no_acked_write_loss(self) -> dict:
        c = self.cluster
        lost, checked = [], 0
        for (pool, oid), (version, payload) in sorted(c.acked.items()):
            got = c.read(pool, oid)
            checked += 1
            if got is None or got[0] < version:
                lost.append((pool, oid, version,
                             None if got is None else got[0]))
                continue
            gv, gp = got
            want = f"{oid}:{gv}:".encode()
            if gv == version and gp != payload:
                lost.append((pool, oid, version, "corrupt"))
            elif gv > version and not gp.startswith(want[:len(gp)]):
                lost.append((pool, oid, version, f"corrupt@{gv}"))
        assert not lost, (
            f"ACKED WRITE LOSS: {lost[:5]} (+{max(0, len(lost)-5)} more); "
            f"{self._recipe()}")
        return {"checked": checked, "lost": 0}

    # 2 ---------------------------------------------------------------------
    def check_pgs_clean(self) -> dict:
        c = self.cluster
        degraded = c._degraded_by_pg()
        assert not degraded, (
            f"PGS NOT CLEAN after quiesce: {dict(sorted(degraded.items())[:5])}; "
            f"{self._recipe()}")
        m = c.osdmap()
        arrays = {pid: np.asarray(m.map_pool(pid)[0]) for pid in m.pools}
        pgs = 0
        for pid, ps in sorted(c._touched_pgs()):
            if pid not in arrays or ps >= arrays[pid].shape[0]:
                continue
            acting = [int(o) for o in arrays[pid][ps] if o >= 0]
            views = [
                {o: v for o, (v, _pl) in
                 (c.stubs[s].store.get((pid, ps)) or {}).items()}
                for s in acting
            ]
            assert all(v == views[0] for v in views[1:]), (
                f"PG {pid}.{ps} shards diverge after quiesce; "
                f"{self._recipe()}")
            pgs += 1
        return {"pgs": pgs, "degraded": 0}

    # 3 ---------------------------------------------------------------------
    def check_forecast_vs_observed(self) -> dict:
        r = dict(self.cluster.remap)
        fc, ob = r["forecast_shards"], r["observed_shards"]
        tol = max(CHURN_FLOOR, CHURN_TOLERANCE * ob)
        assert abs(fc - ob) <= tol, (
            f"REMAP FORECAST DRIFT: forecast={fc} observed={ob} "
            f"tolerance={tol:.1f} over {r['events']} map changes; "
            f"{self._recipe()}")
        r["tolerance"] = tol
        return r

    # 4 ---------------------------------------------------------------------
    def check_controller_oscillation(self, max_flips: int = 2) -> int:
        flips = controller_flip_count()
        assert flips <= max_flips, (
            f"QOS CONTROLLER OSCILLATES: {flips} window direction flips "
            f"after settling (max {max_flips}); {self._recipe()}")
        return flips

    # 5 ---------------------------------------------------------------------
    def check_class_conservation(self) -> dict:
        c = self.cluster
        total_enqueued = total_classes = 0
        for i, s in sorted(c.stubs.items()):
            d = s.scheduler.dump()
            served = sum(row["served"] for row in d["classes"].values())
            depth = sum(row["depth"] for row in d["classes"].values())
            accounted = served + depth + d["retired_served"]
            assert accounted == s.enqueued, (
                f"QOS CLASS LEAK on osd.{i}: enqueued={s.enqueued} "
                f"served={served} depth={depth} "
                f"retired_served={d['retired_served']}; {self._recipe()}")
            assert d["dynamic_classes"] <= d["max_dynamic"], (
                f"DYNAMIC CLASS OVERFLOW on osd.{i}: "
                f"{d['dynamic_classes']} > {d['max_dynamic']}; "
                f"{self._recipe()}")
            total_enqueued += s.enqueued
            total_classes += d["dynamic_classes"]
        return {"enqueued": total_enqueued,
                "dynamic_classes": total_classes}

    # 6 ---------------------------------------------------------------------
    def check_health_symmetry(self) -> dict:
        c = self.cluster
        still = sorted(set(c.health_checks()) & c.raised_checks)
        assert not still, (
            f"HEALTH CHECKS STUCK after quiesce: {still}; "
            f"{self._recipe()}")
        return {"raised": sorted(c.raised_checks), "stuck": []}

    # 7 ---------------------------------------------------------------------
    def check_replay_determinism(self) -> dict:
        p = self.planner
        twin = StormPlanner(
            cluster=None, seed=p.seed, n_stubs=p.n_stubs,
            n_mons=p.n_mons, racks=p.racks,
            osds_per_host=p.osds_per_host, pool=p.pool,
            n_tenants=p.n_tenants,
            objects_per_tenant=p.objects_per_tenant,
            max_dead_frac=p.max_dead_frac, max_splits=p.max_splits)
        events = twin.plan(len(p.events))
        assert events == p.events, (
            f"REPLAY DIVERGENCE: twin plan differs at event "
            f"{next(i for i, (a, b) in enumerate(zip(events, p.events)) if a != b)}; "
            f"{self._recipe()}")
        digest = twin.plan_digest()
        assert digest == p.plan_digest(), (
            f"REPLAY DIGEST MISMATCH: {digest} != {p.plan_digest()}; "
            f"{self._recipe()}")
        return {"events": len(events), "digest": digest}


def run_remap_storm(n_osds: int = 64, pg_num: int = 1024,
                    seed: int = 0, rounds: int = 4,
                    sample: int = 256, size: int = 3) -> dict:
    """Remap storm on a bare OSDMap (no daemons): each round marks a
    random cohort of OSDs out (or back in), forecasts the churn with
    batched :func:`diff_mappings`, and cross-checks the batched mapping
    against the scalar ``pg_to_up_acting_osds`` path on a seeded PG
    sample.  Scales to 1M PGs (the ``-m slow`` soak / CLI) because the
    forecast is one batched CRUSH evaluation per round.

    Returns a report; raises AssertionError if batched and scalar
    mappings disagree on the sample, or forecast drifts >10% from the
    batched observation.
    """
    rng = random.Random(seed)
    hosts = -(-n_osds // 4)
    m = OSDMap(CrushWrapper(build_hierarchical_map(hosts, 4, racks=4)),
               max_osd=n_osds)
    m.create_pool(1, pg_num=pg_num, size=size, crush_rule=0,
                  name="remapstorm")
    pgs = sorted(rng.sample(range(pg_num), min(sample, pg_num)))
    out: list[int] = []
    total_fc = total_ob = 0
    for rd in range(rounds):
        prev, _ = m.map_pool(1)
        prev = np.asarray(prev)
        if rd % 2 == 0 or not out:
            cohort = rng.sample(
                [o for o in range(n_osds) if o not in out],
                max(1, n_osds // 16))
            for o in cohort:
                m.mark_out(o)
            out.extend(cohort)
        else:
            back = rng.sample(out, max(1, len(out) // 2))
            for o in back:
                m.mark_in(o)
            out = [o for o in out if o not in back]
        cur, _ = m.map_pool(1)
        cur = np.asarray(cur)
        fc = diff_mappings(m, {1: prev}, {1: cur})
        # observed churn straight off the batched arrays (membership)
        ob = int((~(cur[:, :, None] == prev[:, None, :]).any(axis=2)
                  & (cur >= 0)).sum())
        total_fc += int(fc["shards_remapped"])
        total_ob += ob
        # independent-path cross-check: scalar mapper on the PG sample
        for ps in pgs:
            u, _up, _a, _ap = m.pg_to_up_acting_osds(1, ps)
            su = [o for o in u if o >= 0]
            bu = [int(o) for o in cur[ps] if o >= 0]
            assert su == bu, (
                f"BATCHED/SCALAR MAPPING DIVERGENCE pg 1.{ps} round "
                f"{rd}: scalar={su} batched={bu} seed={seed}")
    tol = max(CHURN_FLOOR, CHURN_TOLERANCE * total_ob)
    assert abs(total_fc - total_ob) <= tol, (
        f"REMAP FORECAST DRIFT: forecast={total_fc} observed={total_ob} "
        f"tolerance={tol:.1f} seed={seed}")
    return {
        "n_osds": n_osds, "pg_num": pg_num, "rounds": rounds,
        "seed": seed, "sampled_pgs": len(pgs),
        "forecast_shards": total_fc, "observed_shards": total_ob,
        "tolerance": tol,
    }
