"""CLI: ``python -m ceph_tpu.qa.storm`` — run a failure storm or a
bare remap storm and print the invariant report as JSON.

    python -m ceph_tpu.qa.storm --stubs 250 --events 400 --seed 1
    python -m ceph_tpu.qa.storm remap --osds 512 --pgs 1048576
"""
from __future__ import annotations

import argparse
import json
import sys

from . import StormCluster, StormInvariantChecker, StormPlanner, \
    run_remap_storm


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph_tpu.qa.storm")
    ap.add_argument("mode", nargs="?", default="storm",
                    choices=("storm", "remap"))
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--stubs", type=int, default=250)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--racks", type=int, default=4)
    ap.add_argument("--events", type=int, default=400)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--pg-num", type=int, default=64)
    ap.add_argument("--osds", type=int, default=512,
                    help="remap mode: bare-map OSD count")
    ap.add_argument("--pgs", type=int, default=65536,
                    help="remap mode: bare-map pg_num (1M for the soak)")
    args = ap.parse_args(argv)
    if args.mode == "remap":
        report = run_remap_storm(n_osds=args.osds, pg_num=args.pgs,
                                 seed=args.seed)
        print(json.dumps(report, indent=2))
        return 0
    with StormCluster(n_stubs=args.stubs, n_mons=args.mons,
                      racks=args.racks) as c:
        c.create_pool("stormdata", size=3, pg_num=args.pg_num,
                      min_size=2)
        p = StormPlanner(cluster=c, seed=args.seed,
                         n_tenants=args.tenants)
        p.run(args.events)
        p.quiesce()
        report = StormInvariantChecker(c, p).check()
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
