"""cephstorm — thousand-OSD failure-storm simulation with invariant
gates (docs/storm_sim.md).

The storm harness scales the PR-1 thrasher discipline (pure seeded
``plan()``, executed against real control planes, gated by an invariant
checker) past what real OSD daemons can host in one process: hundreds
to thousands of :class:`~ceph_tpu.qa.storm.stub.StubOSD` objects — an
in-memory data plane honoring version/ack semantics — under REAL
monitors (Paxos, OSDMap mutation, health checks), a REAL mgr (digest
pipeline), real CRUSH placement (batched + scalar paths cross-checked),
and the production mClock scheduler per stub.

    from ceph_tpu.qa.storm import StormCluster, StormPlanner, \
        StormInvariantChecker
    with StormCluster(n_stubs=250, racks=4) as c:
        p = StormPlanner(cluster=c, seed=1)
        p.run(400)
        p.quiesce()
        StormInvariantChecker(c, p).check()
"""
from .cluster import StormCluster
from .invariants import StormInvariantChecker, run_remap_storm
from .planner import StormPlanner
from .stub import SimClock, StubOSD

__all__ = [
    "SimClock",
    "StormCluster",
    "StormInvariantChecker",
    "StormPlanner",
    "StubOSD",
    "run_remap_storm",
]
