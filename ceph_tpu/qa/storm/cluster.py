"""StormCluster — real control planes over hundreds of stub OSDs.

The monitors, Paxos, OSDMap mutation path, health-check assembly and
the mgr digest pipeline are the PRODUCTION daemons (the same objects
LocalCluster runs); only the OSDs are stubs.  Stubs need no boot
protocol: a fresh OSDMap marks every OSD EXISTS|UP and IN, so the
initial map handed to the monitors presents all N stubs as up.  Kill
is the mon path (``osd down`` + ``osd out``), revive re-enters through
the leader's ``handle_boot`` (the only path that marks up) plus
``osd in`` — every map change is a committed Paxos proposal, exactly
the churn a real failure storm generates.

The data plane is client-driven: :meth:`write` maps the object through
the CURRENT map's scalar path, fans the shard write out to acting
stubs (each recv gated by the ``storm.stub.recv`` failpoint — rack
netsplits arm two match entries per split), and acks iff ``min_size``
shards committed — the ``acked`` dict is the no-acked-write-loss
contract the checker holds the storm to.

Forecast-vs-observed: every map-changing event snapshots the batched
``map_pool`` arrays before/after and accumulates a
:func:`~ceph_tpu.osd.placement.diff_mappings` forecast next to the
scalar churn count (independent mapping path) — the checker's <=10%
agreement gate, placement_smoke's comparison at storm scale.
"""
from __future__ import annotations

import time

import numpy as np

from ...client.rados import Rados
from ...common.context import CephContext
from ...common.failpoint import registry
from ...crush import CrushWrapper, build_hierarchical_map
from ...mgr import MgrDaemon
from ...mon import MonMap, Monitor
from ...osd.osdmap import OSDMap, object_ps
from ...osd.placement import diff_mappings
from ..vstart import _free_addrs
from .stub import SimClock, StubOSD


def storm_payload(oid: str, version: int, size: int) -> bytes:
    """The deterministic payload of (oid, version) — the planner never
    ships bytes, so replay needs no payload log."""
    seedb = f"{oid}:{version}:".encode()
    reps = -(-size // len(seedb))
    return (seedb * reps)[:size]


class StormCluster:
    def __init__(self, n_stubs: int = 250, n_mons: int = 1,
                 racks: int = 4, osds_per_host: int = 4,
                 max_dynamic: int = 32,
                 conf_overrides: dict | None = None,
                 with_mgr: bool = True):
        self.n_stubs = n_stubs
        self.n_mons = n_mons
        self.racks = max(1, racks)
        self.osds_per_host = osds_per_host
        self.max_dynamic = max_dynamic
        self.with_mgr = with_mgr
        self.conf_overrides = {
            # storms out explicitly; the grace must not race the plan
            "mon_osd_down_out_interval": 3600.0,
            "mgr_digest_interval": 0.2,
            "mgr_modules": "status",
            **(conf_overrides or {}),
        }
        self.clock = SimClock()
        self.mons: dict[str, Monitor] = {}
        self.mgr: MgrDaemon | None = None
        self.stubs: dict[int, StubOSD] = {}
        self.mon_addrs: list = []
        self._admin: Rados | None = None
        #: (pool_name, oid) -> (version, payload) for every ACKED write
        self.acked: dict[tuple[str, str], tuple[int, bytes]] = {}
        #: (pool_name, oid) -> highest version ever issued (write path)
        self._version_counters: dict[tuple[str, str], int] = {}
        #: armed rack splits: (rack_a, rack_b) -> [entry ids]
        self._split_tokens: dict[tuple[int, int], list[int]] = {}
        #: accumulated remap churn: forecast (batched diff_mappings)
        #: vs observed (scalar pg_to_up_acting churn), in shards
        self.remap = {"events": 0, "forecast_shards": 0,
                      "observed_shards": 0}
        #: health checks seen raised during the storm (the raise half
        #: of the raise-and-clear symmetry invariant)
        self.raised_checks: set[str] = set()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StormCluster":
        hosts = -(-self.n_stubs // self.osds_per_host)
        cmap = build_hierarchical_map(hosts, self.osds_per_host,
                                      racks=self.racks)
        initial = OSDMap(CrushWrapper(cmap), max_osd=self.n_stubs)
        addrs = _free_addrs(self.n_mons)
        self.mon_addrs = [list(a) for a in addrs]
        names = [chr(ord("a") + i) for i in range(self.n_mons)]
        monmap = MonMap({names[i]: addrs[i] for i in range(self.n_mons)})
        for nm in names:
            cct = CephContext(f"mon.{nm}",
                              overrides=dict(self.conf_overrides))
            mon = Monitor(cct, nm, monmap, initial_osdmap=initial)
            self.mons[nm] = mon
            mon.start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not any(
                m.is_leader() for m in self.mons.values()):
            time.sleep(0.05)
        if not any(m.is_leader() for m in self.mons.values()):
            raise TimeoutError("no mon leader")
        if self.with_mgr:
            self.mgr = MgrDaemon(
                CephContext("mgr", overrides=dict(self.conf_overrides)),
                self.mon_addrs)
            self.mgr.start()
        per = max(1, hosts // self.racks)
        for i in range(self.n_stubs):
            host = i // self.osds_per_host
            rack = min(host // per, self.racks - 1)
            self.stubs[i] = StubOSD(i, rack, host, self.clock,
                                    max_dynamic=self.max_dynamic)
        self._admin = Rados(
            CephContext("client.storm-admin",
                        overrides=dict(self.conf_overrides)),
            self.mon_addrs, name="client.storm-admin")
        self._admin.connect()
        return self

    def stop(self) -> None:
        for pair in list(self._split_tokens):
            self.heal_racks(*pair)
        # each step best-effort: a wedged admin client must not strand
        # the mgr/mon teardown behind it (mgr/daemon.py style)
        if self._admin is not None:
            try:
                self._admin.shutdown()
            except Exception as e:
                print(f"storm: admin client shutdown raised: {e!r}")
            self._admin = None
        if self.mgr is not None:
            try:
                self.mgr.shutdown()
            except Exception as e:
                print(f"storm: mgr shutdown raised: {e!r}")
        for mon in self.mons.values():
            mon.shutdown()

    def __enter__(self) -> "StormCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- control plane -----------------------------------------------------
    def _leader(self) -> Monitor:
        for m in self.mons.values():
            if m.is_leader():
                return m
        raise RuntimeError("no leader")

    def mon_command(self, cmd: dict, tries: int = 3):
        for i in range(tries):
            try:
                rv, res = self._admin.command(cmd)
                if rv == 0 or i == tries - 1:
                    return rv, res
            except (IOError, OSError, TimeoutError):
                if i == tries - 1:
                    raise
            time.sleep(0.2 * (i + 1))
        return rv, res

    def osdmap(self) -> OSDMap:
        m = self._leader().osdmon.osdmap
        assert m is not None, "no committed osdmap"
        return m

    def create_pool(self, name: str, size: int = 3, pg_num: int = 32,
                    min_size: int | None = None) -> int:
        rv, res = self.mon_command({
            "prefix": "osd pool create", "name": name, "pg_num": pg_num,
            "size": size,
            **({"min_size": min_size} if min_size is not None else {}),
        })
        assert rv == 0, (rv, res)
        self.mon_command({"prefix": "osd pool application enable",
                          "pool": name, "app": "rados"})
        return (res or {}).get("pool_id")

    def pool_id(self, name: str) -> int:
        m = self.osdmap()
        return next(i for i, p in m.pools.items() if p.name == name)

    # -- failure plane -----------------------------------------------------
    #: scalar ground-truth PGs cross-checked per map change — the
    #: independent mapping path pinning the batched arrays
    SCALAR_SAMPLE = 4

    def _map_change(self, fn) -> None:
        """Run one map-mutating closure between batched mapping
        snapshots; accumulate the diff_mappings forecast next to the
        observed membership churn, and pin a rotating sample of PGs to
        the scalar reference mapper (independent-path cross-check —
        the full scalar sweep is what a thousand-stub storm cannot
        afford per event)."""
        prev = self._batched_mappings()
        fn()
        m = self.osdmap()
        cur = self._batched_mappings()
        fc = diff_mappings(m, prev, cur)
        observed = 0
        for pid in set(prev) & set(cur):
            a, b = prev[pid], cur[pid]
            observed += int((~(b[:, :, None] == a[:, None, :]).any(axis=2)
                             & (b >= 0)).sum())
        ev = self.remap["events"]
        for pid, b in sorted(cur.items()):
            pg_num = b.shape[0]
            for k in range(min(self.SCALAR_SAMPLE, pg_num)):
                ps = (ev * 7 + k * 13) % pg_num
                u, _up, _a, _ap = m.pg_to_up_acting_osds(pid, ps)
                su = [o for o in u if o >= 0]
                bu = [int(x) for x in b[ps] if x >= 0]
                assert su == bu, (
                    f"batched/scalar mapping divergence pg {pid}.{ps}: "
                    f"scalar={su} batched={bu}")
        self.remap["events"] = ev + 1
        self.remap["forecast_shards"] += int(fc["shards_remapped"])
        self.remap["observed_shards"] += observed

    def _batched_mappings(self) -> dict:
        """{pool_id: up[pg_num, size] ndarray} via the batched mapper."""
        return {pid: up for pid, (up, _p) in
                self._pool_arrays().items()}

    def _pool_arrays(self) -> dict:
        """{pool_id: (up, up_primary) ndarrays}, cached per osdmap
        epoch — ticks between map changes reuse one batched CRUSH
        evaluation instead of re-launching it per tick."""
        m = self.osdmap()
        cached = self.__dict__.get("_pool_array_cache")
        if cached is not None and cached[0] == m.epoch:
            return cached[1]
        arrays = {pid: tuple(np.asarray(a) for a in m.map_pool(pid))
                  for pid in m.pools}
        self._pool_array_cache = (m.epoch, arrays)
        return arrays

    def kill_stub(self, i: int) -> None:
        stub = self.stubs[i]
        if not stub.alive:
            return
        stub.alive = False

        def out():
            self.mon_command({"prefix": "osd down", "id": i})
            self.mon_command({"prefix": "osd out", "id": i})
        self._map_change(out)

    def revive_stub(self, i: int) -> None:
        stub = self.stubs[i]
        if stub.alive:
            return
        stub.alive = True

        def back():
            self._leader().osdmon.handle_boot(i, ("127.0.0.1", 0))
            self.mon_command({"prefix": "osd in", "id": i})
        self._map_change(back)

    def kill_rack(self, rack: int) -> None:
        """Cascading rack failure — one map-change burst, one down and
        one out proposal (the batched `ids` form) however many stubs
        the rack holds."""
        victims = [i for i, s in sorted(self.stubs.items())
                   if s.rack == rack and s.alive]
        if not victims:
            return
        for i in victims:
            self.stubs[i].alive = False

        def out():
            self.mon_command({"prefix": "osd down", "ids": victims})
            self.mon_command({"prefix": "osd out", "ids": victims})
        self._map_change(out)

    def revive_rack(self, rack: int) -> None:
        back = [i for i, s in sorted(self.stubs.items())
                if s.rack == rack and not s.alive]
        if not back:
            return
        for i in back:
            self.stubs[i].alive = True

        def boot():
            osdmon = self._leader().osdmon
            for i in back:
                osdmon.handle_boot(i, ("127.0.0.1", 0))
            self.mon_command({"prefix": "osd in", "ids": back})
        self._map_change(boot)

    def reweight(self, osd: int, weight: float) -> None:
        self._map_change(lambda: self.mon_command(
            {"prefix": "osd reweight", "id": osd, "weight": weight}))

    def split_racks(self, a: int, b: int) -> None:
        """Recv-drop netsplit between two racks: O(1) failpoint entries
        per direction, whatever the rack population."""
        if (a, b) in self._split_tokens:
            return
        reg = registry()
        toks = []
        for src, dst in ((a, b), (b, a)):
            toks.append(reg.add("storm.stub.recv", "error",
                                match={"src_rack": src, "dst_rack": dst}))
        self._split_tokens[(a, b)] = toks

    def heal_racks(self, a: int, b: int) -> None:
        for eid in self._split_tokens.pop((a, b), []):
            registry().remove("storm.stub.recv", eid=eid)

    def mon_churn(self, name: str) -> None:
        mon = self.mons.get(name)
        if mon is not None:
            mon.elector.start_election()

    # -- data plane --------------------------------------------------------
    def write(self, pool: str, oid: str, size: int,
              client_key: str | None = None) -> bool:
        """One client write through the current map: fan the versioned
        payload out to the acting stubs; ack iff >= min_size committed.
        Returns the ack; acked writes land in ``self.acked``."""
        m = self.osdmap()
        pid = self.pool_id(pool)
        p = m.pools[pid]
        ps = object_ps(oid, p.pg_num)
        _up, _upp, acting, primary = m.pg_to_up_acting_osds(pid, ps)
        if primary < 0:
            return False
        vkey = (pool, oid)
        version = self._version_counters.get(vkey, 0) + 1
        self._version_counters[vkey] = version
        payload = storm_payload(oid, version, size)
        src = self.stubs[primary]
        if not src.alive:
            return False
        durable = 0
        for o in acting:
            if o < 0:
                continue
            dst = self.stubs[o]
            if o != primary and not dst.reachable_from(src):
                continue
            if dst.apply_write(pid, ps, oid, version, payload,
                               client_key=client_key):
                durable += 1
        min_size = p.min_size or (p.size // 2 + 1)
        if durable >= min_size:
            self.acked[vkey] = (version, payload)
            return True
        return False

    def read(self, pool: str, oid: str) -> tuple[int, bytes] | None:
        """Newest stored (version, payload) among reachable acting
        shards, primary's view — None when nothing is reachable."""
        m = self.osdmap()
        pid = self.pool_id(pool)
        p = m.pools[pid]
        ps = object_ps(oid, p.pg_num)
        _up, _upp, acting, primary = m.pg_to_up_acting_osds(pid, ps)
        if primary < 0 or not self.stubs[primary].alive:
            return None
        src = self.stubs[primary]
        best = None
        for o in acting:
            if o < 0:
                continue
            dst = self.stubs[o]
            if o != primary and not dst.reachable_from(src):
                continue
            got = dst.lookup(pid, ps, oid)
            if got is not None and (best is None or got[0] > best[0]):
                best = got
        return best

    # -- ticks: time, QoS drain, mgr feed, health poll ---------------------
    def tick(self, dt: float = 0.5) -> None:
        self.clock.advance(dt)
        degraded, primaries = self._degraded_by_pg(with_primaries=True)
        by_primary: dict[int, dict[str, int]] = {}
        for pgid, n in degraded.items():
            by_primary.setdefault(primaries[pgid], {})[pgid] = n
        for i, s in sorted(self.stubs.items()):
            if not s.alive:
                continue
            s.drain()
            if self.mgr is not None:
                self.mgr.ingest_local_report(
                    f"osd.{i}", s.mgr_counters(),
                    stats=s.mgr_stats(by_primary.get(i, {})))
        for check in self.health_checks():
            self.raised_checks.add(check)

    def _touched_pgs(self) -> set[tuple[int, int]]:
        """(pool_id, ps) pairs holding objects on ANY stub — the only
        PGs degraded/recovery scans need to visit."""
        touched: set[tuple[int, int]] = set()
        for s in self.stubs.values():
            for key, objs in s.store.items():
                if objs:
                    touched.add(key)
        return touched

    def _newest_by_pg(self) -> dict[tuple[int, int],
                                    dict[str, tuple[int, bytes]]]:
        """{(pool_id, ps): {oid: newest (version, payload)}} across
        EVERY stub's store, not just the current acting set.  Stores
        survive kills, so any holder is a legal recovery source — the
        sim analog of past-interval peers: reweight churn can remap a
        PG's whole acting set away from the shards that took a write,
        and recovery must still find those bytes."""
        newest: dict[tuple[int, int], dict[str, tuple[int, bytes]]] = {}
        for s in self.stubs.values():
            for key, objs in s.store.items():
                dst = newest.setdefault(key, {})
                for oid, rec in objs.items():
                    if oid not in dst or rec[0] > dst[oid][0]:
                        dst[oid] = rec
        return newest

    def _degraded_by_pg(self, with_primaries: bool = False):
        """{pgid: missing object copies} — acting shards missing objects
        (or holding stale versions) relative to the newest holder.  One
        batched CRUSH evaluation per pool; only object-holding PGs are
        scanned, so cost tracks data, not pg_num x stubs."""
        m = self.osdmap()
        arrays = self._pool_arrays()
        out: dict[str, int] = {}
        prim: dict[str, int] = {}
        for (pid, ps), recs in sorted(self._newest_by_pg().items()):
            pool = m.pools.get(pid)
            if pool is None or ps >= pool.pg_num:
                continue
            up, upp = arrays[pid]
            live = [int(o) for o in up[ps] if o >= 0]
            newest = {oid: rec[0] for oid, rec in recs.items()}
            if not newest:
                continue
            deg = 0
            for o in live:
                objs = self.stubs[o].store.get((pid, ps)) or {}
                for oid, v in newest.items():
                    got = objs.get(oid)
                    if got is None or got[0] < v:
                        deg += 1
            deg += len(newest) * max(0, pool.size - len(live))
            if deg:
                pgid = f"{pid}.{ps}"
                out[pgid] = deg
                prim[pgid] = int(upp[ps])
        return (out, prim) if with_primaries else out

    def health_checks(self) -> dict:
        try:
            rv, st = self.mon_command({"prefix": "status"}, tries=1)
        except (IOError, OSError, TimeoutError):
            return {}
        if rv != 0:
            return {}
        return (st.get("health") or {}).get("checks") or {}

    # -- quiesce + recovery ------------------------------------------------
    def quiesce(self, timeout: float = 60.0) -> None:
        """Heal every split, revive every stub, run sim recovery (copy
        newest versions onto every acting shard), drain, and wait for
        the raised health checks to clear — the checker precondition."""
        for pair in list(self._split_tokens):
            self.heal_racks(*pair)
        for i, s in sorted(self.stubs.items()):
            if not s.alive:
                self.revive_stub(i)
        self.recover()
        self.tick(1.0)
        while any(s.scheduler.qlen() for s in self.stubs.values()):
            self.tick(1.0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.tick(0.0)
            live = set(self.health_checks()) & self.raised_checks
            if not live:
                return
            time.sleep(0.3)
        raise TimeoutError(
            f"health checks never cleared: "
            f"{sorted(set(self.health_checks()) & self.raised_checks)}")

    def recover(self) -> None:
        """Copy each object's newest (version, payload) onto every
        acting shard — the sim analog of log/backfill recovery."""
        m = self.osdmap()
        arrays = {pid: up for pid, (up, _p) in
                  self._pool_arrays().items()}
        for (pid, ps), newest in sorted(self._newest_by_pg().items()):
            pool = m.pools.get(pid)
            if pool is None or ps >= pool.pg_num:
                continue
            live = [int(o) for o in arrays[pid][ps] if o >= 0]
            for o in live:
                objs = self.stubs[o].store.setdefault((pid, ps), {})
                for oid, rec in newest.items():
                    cur = objs.get(oid)
                    if cur is None or cur[0] < rec[0]:
                        objs[oid] = rec
