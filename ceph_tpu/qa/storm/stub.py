"""Stub OSD + simulation clock — the storm harness's data plane.

A :class:`StubOSD` is what a thousand-daemon storm can afford per OSD:
an in-memory versioned object store, the PRODUCTION
:class:`~ceph_tpu.osd.scheduler.MClockScheduler` (clock-injected, so
the sim drives time), and one failpoint seam (``storm.stub.recv``) at
the receive path so netsplits between racks are armed exactly like the
thrasher's per-OSD ``msgr.frame.recv`` drops — but with rack-level
match keys, O(1) entries per split however many OSDs a rack holds.

What is REAL: the QoS scheduler (per-(client,pool) dynamic classes,
LRU retirement, the thrash surface under test).  What is STUBBED: the
wire and the store.  The stub's ack/version semantics are the part the
referee test (tests/test_storm.py) holds against a real OSD: a write
carries an explicit version; newer versions overwrite, replays of the
stored version are idempotent acks, and OLDER versions are refused —
the object_info_t version guard every sub-op reply honors.
"""
from __future__ import annotations

from ...common.failpoint import failpoint
from ...osd.scheduler import MClockScheduler, QoSParams


class SimClock:
    """Monotonic simulated time the scheduler's tags run on — the storm
    advances it explicitly (tick events), so schedules are a function
    of the plan, not of wall-clock scheduling jitter."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        assert dt >= 0.0, dt
        self._now += dt
        return self._now


class StubOSD:
    """One storm OSD: alive flag, rack/host identity, versioned object
    store keyed by (pool, ps, oid), and a real mClock scheduler."""

    def __init__(self, osd_id: int, rack: int, host: int,
                 clock: SimClock, max_dynamic: int = 32):
        self.id = osd_id
        self.rack = rack
        self.host = host
        self.clock = clock
        self.alive = True
        #: (pool_id, ps) -> {oid: (version, payload)} — survives kill
        #: (the in-memory stash semantics LocalCluster.kill_osd keeps)
        self.store: dict[tuple[int, int], dict[str, tuple[int, bytes]]] = {}
        #: class-conservation counter: every accepted op bumps it
        self.enqueued = 0
        self.scheduler = MClockScheduler(
            {"client": QoSParams(weight=1.0),
             "background_recovery": QoSParams(weight=0.5)},
            clock=clock.now, max_dynamic=max_dynamic,
            dynamic_params=QoSParams(weight=1.0))

    # -- the wire seam -----------------------------------------------------
    def reachable_from(self, src: "StubOSD") -> bool:
        """Evaluate the ``storm.stub.recv`` failpoint for a frame from
        `src` — the one injection point rack netsplits arm.  Dead stubs
        drop everything; an armed matching entry raises and the frame
        is lost (sender sees no ack, exactly a recv-drop split)."""
        if not self.alive:
            return False
        try:
            failpoint("storm.stub.recv",
                      entity=f"osd.{self.id}", peer=f"osd.{src.id}",
                      src_rack=src.rack, dst_rack=self.rack)
        except Exception:
            return False
        return True

    def apply_write(self, pool_id: int, ps: int, oid: str,
                    version: int, payload: bytes,
                    client_key: str | None = None) -> bool:
        """Commit one shard write.  Returns True when the write is
        DURABLE here (ack semantics): version > stored applies, version
        == stored is an idempotent replay ack, version < stored is a
        stale refusal.  The op also rides the scheduler under the
        client's dynamic class so QoS accounting sees real traffic."""
        objs = self.store.setdefault((pool_id, ps), {})
        cur = objs.get(oid)
        if cur is not None and version < cur[0]:
            return False
        if cur is None or version > cur[0]:
            objs[oid] = (version, payload)
        cls = (self.scheduler.client_class(client_key)
               if client_key else "client")
        self.scheduler.enqueue(cls, (oid, version))
        self.enqueued += 1
        return True

    def lookup(self, pool_id: int, ps: int,
               oid: str) -> tuple[int, bytes] | None:
        return self.store.get((pool_id, ps), {}).get(oid)

    def drain(self, max_ops: int | None = None) -> int:
        """Serve queued ops non-blocking at the CURRENT sim time (tick
        events advance the clock first).  Returns ops served."""
        served = 0
        while max_ops is None or served < max_ops:
            got = self.scheduler.dequeue(timeout=0)
            if got is None:
                break
            served += 1
        return served

    # -- telemetry the real mgr ingests ------------------------------------
    def mgr_stats(self, degraded_by_pg: dict[str, int]) -> dict:
        """The ``stats`` half of an MMgrReport: pg_info rows for PGs this
        stub primaries (the digest's PG_DEGRADED source) + statfs."""
        return {
            "statfs": {"total": 1 << 30, "available": 1 << 29},
            "pg_info": {
                pgid: {"degraded": n} for pgid, n in degraded_by_pg.items()
            },
        }

    def mgr_counters(self) -> dict:
        d = self.scheduler.dump()
        return {"osd": {"op_w": self.enqueued},
                "mclock": {"qlen": self.scheduler.qlen(),
                           "dynamic_classes": d["dynamic_classes"],
                           "retired": d["retired"]}}
