"""cephqos CI smoke: the bully scenario, controller off vs on, on a
real CPU LocalCluster (qa/ci_gate.sh step 8; ISSUE 12 acceptance).

Two identical mixed-population runs (``bench/traffic.py
run_bully_traffic``: one heavy streamer driving several closed-loop
64 KiB write streams against N small open-loop Poisson writers), the
first with every cephqos mechanism DISABLED (one static mClock class,
no per-client batcher share, controller inert — the pre-cephqos data
plane), the second with the full closed loop armed: dynamic per-client
mClock classes, bounded client-op slots, the batcher admission share,
and the live mgr controller observing its own telemetry and pushing
MQoSSettings.

Gates (the ISSUE's bars):

- worst-victim ``victim_satisfaction`` (achieved/offered ops for the
  open-loop victims) must hold an absolute >=0.5 floor with the
  controller on — a starved victim scores << 0.5, a served one ~1.0
  modulo Poisson arrival noise.  (Raw max/min-ops ``fairness_ratio``
  is reported but NOT gated: against a closed-loop bully it moves the
  wrong way whenever the controller speeds the whole cluster up);
- aggregate GiB/s must stay within 10% of the controller-off run
  (fairness must not be bought with throughput);
- pooled victim p99 must improve >= 1.5x (typical measured ~3x; the
  acceptance headline is 2x and the JSON carries the exact ratio);
- the controller must have actually closed the loop: settings pushes
  applied (qos_epoch > 0 on the OSDs' view via qos_status) and at
  least one client classed heavy at some point (decisions ring).

Exit 0 on success; 1 with a ``problems`` list otherwise.  Prints one
JSON summary on stdout (the gate archives it next to the SARIF
artifacts).
"""
from __future__ import annotations

import json
import sys


def main() -> int:
    import jax

    # this box's sitecustomize pins the tunneled TPU backend and IGNORES
    # the JAX_PLATFORMS env var; config.update is the reliable spelling
    jax.config.update("jax_platforms", "cpu")

    from ..bench.traffic import run_bully_traffic

    problems: list[str] = []
    scenario = dict(n_small=3, seconds=4.0, bully_streams=6,
                    small_rate=10.0)
    off = run_bully_traffic(qos=False, **scenario)
    on = run_bully_traffic(qos=True, settle=2.0, **scenario)

    # -- no victim starved ----------------------------------------------
    # Worst-victim satisfaction (achieved/offered for the open-loop
    # victims) as an ABSOLUTE floor: a starved victim scores << 0.5, a
    # served one ~1.0 modulo Poisson arrival noise (~15%/run — which is
    # why this is a floor, not an off-vs-on delta).  Max/min ops
    # (fairness_ratio) is not gated at all: the bully is closed-loop,
    # so a controller that speeds the cluster up grows bully ops
    # against the rate-capped victims and pushes max/min the WRONG way
    # even as every victim gets strictly better service.  The p99 gate
    # below carries the "fairness improved" claim.
    s_on = on.get("victim_satisfaction")
    if s_on is None:
        problems.append(
            "controller-on run has no victim satisfaction sample")
    elif s_on < 0.5:
        problems.append(
            f"a victim is starved with the controller on: worst-victim "
            f"satisfaction {s_on} < 0.5")

    # -- aggregate throughput within 10% --------------------------------
    agg_ratio = None
    if off.get("aggregate_gibps"):
        agg_ratio = round(on["aggregate_gibps"] / off["aggregate_gibps"], 3)
        if agg_ratio < 0.90:
            problems.append(
                f"aggregate GiB/s regressed {1 - agg_ratio:.1%} > 10% "
                f"({off['aggregate_gibps']} -> {on['aggregate_gibps']})")
    else:
        problems.append("controller-off run produced no throughput")

    # -- victim tail latency --------------------------------------------
    p99_ratio = None
    if off.get("victim_p99_ms") and on.get("victim_p99_ms"):
        p99_ratio = round(off["victim_p99_ms"] / on["victim_p99_ms"], 2)
        if p99_ratio < 1.5:
            problems.append(
                f"victim p99 improved only {p99_ratio}x "
                f"({off['victim_p99_ms']} -> {on['victim_p99_ms']} ms), "
                f"want >= 1.5x")
    else:
        problems.append("victim p99 missing from a run")

    # -- the loop actually closed ---------------------------------------
    st = on.get("qos_status") or {}
    if not st.get("qos_epoch"):
        problems.append("controller never pushed settings (qos_epoch 0)")
    if not (st.get("stats") or {}).get("pushes"):
        problems.append("no MQoSSettings deliveries recorded")
    classes = ((on.get("op_queue") or {}).get("classes") or {})
    if not any(c.get("dynamic") and c.get("served")
               for c in classes.values()):
        problems.append("no dynamic per-client class served ops on the "
                        "sampled OSD")

    summary = {
        "off": {k: off.get(k) for k in (
            "aggregate_gibps", "bully_ops", "victim_ops",
            "victim_p50_ms", "victim_p99_ms", "victim_satisfaction",
            "fairness_ratio")},
        "on": {k: on.get(k) for k in (
            "aggregate_gibps", "bully_ops", "victim_ops",
            "victim_p50_ms", "victim_p99_ms", "victim_satisfaction",
            "fairness_ratio")},
        "aggregate_ratio": agg_ratio,
        "victim_p99_improvement": p99_ratio,
        "qos_status": st,
        "problems": problems,
    }
    print(json.dumps(summary))
    for p in problems:
        print(f"# qos smoke FAILED: {p}", file=sys.stderr)
    if not problems:
        print(f"# qos smoke OK: victim p99 {p99_ratio}x better, "
              f"worst-victim satisfaction {s_on}, aggregate "
              f"x{agg_ratio}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
