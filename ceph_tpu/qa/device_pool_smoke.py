"""cephdma CI smoke: control-vs-pool traffic run (qa/ci_gate.sh step
10; ISSUE 14 acceptance).

Runs the PR-8 batcher traffic scenario twice on the CPU backend —
``ec_device_pool=false`` (the historical synchronous flush, the
control) then ``true`` (pooled async encode path) — and compares the
kernel-telemetry deltas:

1. **host-copy bytes per fused flush** (the ``ec_batch_flush`` record)
   must drop >= 50% pool-on vs control: the pooled flush performs only
   the host->device stripe commits, while the control pays host pack +
   packed transfer + full parity fetch.  The deferred commit-point
   fetches stay visible as the ``encode_wait`` sync-point record —
   nothing is hidden, the flusher just stops doing it.
2. **aggregate throughput must not regress**: pooled GiB/s >= 0.85x
   control (CPU noise margin; the ISSUE bar is "does not regress").
3. the flush record flips honest: control flushes are sync points
   (``sync_points`` > 0), pooled flushes are async (their sync moved to
   ``encode_wait``); the pool's own free-list cycle shows hits.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it as device_pool_smoke.json).
"""
from __future__ import annotations

import json
import sys


def _flush_stats() -> dict:
    from ..common.kernel_telemetry import TELEMETRY

    d = TELEMETRY.dump()
    out = {}
    for kern in ("ec_batch_flush", "encode_wait"):
        ks = d.get(kern, {})
        out[kern] = {k: ks.get(k, 0) for k in
                     ("calls", "host_copy_bytes", "sync_points",
                      "bytes_in", "bytes_out")}
    return out


def main(argv=None) -> int:
    from ..bench.traffic import run_traffic
    from ..ops.device_pool import POOL

    problems: list[str] = []
    summary: dict = {"scenario": "device_pool_smoke"}
    runs: dict[str, dict] = {}
    for label, pool_on in (("control", False), ("pool", True)):
        before = _flush_stats()
        pool_before = POOL.stats()
        res = run_traffic(
            "batched", n_clients=4, seconds=2.0, write_size=4096,
            k=8, m=4, qd=4, warmup=0.75,
            conf_overrides={"ec_device_pool": pool_on},
        )
        after = _flush_stats()
        pool_after = POOL.stats()
        delta = {
            kern: {k: after[kern][k] - before[kern][k]
                   for k in after[kern]}
            for kern in after
        }
        flushes = max(1, delta["ec_batch_flush"]["calls"])
        runs[label] = {
            "gibps": res["gibps"],
            "ops": res["ops"],
            "flushes": delta["ec_batch_flush"]["calls"],
            "stripes_per_flush": res["stripes_per_flush"],
            "host_copy_per_flush":
                delta["ec_batch_flush"]["host_copy_bytes"] / flushes,
            "flush_sync_points": delta["ec_batch_flush"]["sync_points"],
            "encode_wait": delta["encode_wait"],
            "pool_hits": pool_after["hits"] - pool_before["hits"],
            "pool_releases":
                pool_after["releases"] - pool_before["releases"],
        }
        summary[label] = runs[label]

    ctl, pool = runs["control"], runs["pool"]
    if ctl["flushes"] <= 0 or pool["flushes"] <= 0:
        problems.append("a run produced no fused flushes")
    if ctl["host_copy_per_flush"] <= 0:
        problems.append("control run recorded no flush host-copy bytes")
    else:
        ratio = pool["host_copy_per_flush"] / ctl["host_copy_per_flush"]
        summary["host_copy_ratio"] = round(ratio, 4)
        if ratio > 0.5:
            problems.append(
                f"host-copy bytes per flush only dropped to "
                f"{ratio:.0%} of control (bar: <= 50%)")
    if ctl["gibps"] > 0 and pool["gibps"] < 0.85 * ctl["gibps"]:
        problems.append(
            f"pooled throughput regressed: {pool['gibps']} vs control "
            f"{ctl['gibps']} GiB/s (bar: >= 0.85x)")
    if ctl["flush_sync_points"] <= 0:
        problems.append("control flushes recorded no sync points")
    if pool["flush_sync_points"] > 0:
        problems.append(
            f"pooled flushes still sync on the flusher "
            f"({pool['flush_sync_points']} sync points)")
    if pool["encode_wait"]["sync_points"] <= 0:
        problems.append("pooled run recorded no encode_wait commit syncs")
    if pool["pool_releases"] <= 0:
        problems.append(
            "pooled run never returned a parity buffer to the pool")

    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
