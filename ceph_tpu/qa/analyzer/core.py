"""cephlint core — findings, suppressions, baseline, and the runner.

The analyzer is the static half of the hygiene story whose runtime half
is common/lockdep.py + common/failpoint.py (reference: Ceph wires
lockdep + clang-analyzer/cppcheck into make check; src/script/run-make.sh
and the smatch/cov scripts).  Five whole-package checks:

    CL1  lock discipline: static lock-order graph, order inversions,
         blocking calls made while a lock is held, raw (lockdep-invisible)
         locks in the concurrency-heavy subsystems
    CL2  shared-state races: read-modify-writes on self attributes of
         multi-threaded classes outside any lock
    CL3  JAX tracing hygiene in ops/, crush/, parallel/, bench/
    CL4  failpoint drift: sites vs KNOWN_FAILPOINTS vs the docs catalogue
    CL5  config-option drift: reads vs common/options.py declarations
    CL6  wire-protocol conformance: encode_payload/decode_payload pairing,
         field loss, MSG_TYPE collisions, dispatch reachability
    CL7  error paths: swallowed exceptions, unbounded blocking waits,
         reset callbacks mutating shared state without the lock
    CL8  kernel shape/dtype abstract interpretation in ops/, gf/, crush/
    CL9  device-topology discipline: ambient jax.devices()/Mesh()/
         default_backend() probes outside the one policy module,
         device-index literals, public jitted entry points in ops/,
         donation without the device-pool seam
    CL10 sharding propagation: a placement lattice (Replicated /
         PartitionSpec-along-axis / Unknown) over parallel/ and ops/,
         flagging implicit reshards, sharded host trips, and
         donation that cannot alias its output
    CL11 seeded determinism / purity: ambient RNG, wall-clock reads on
         the pure-plan call graph, unordered-collection iteration on
         the plan path, and self/global mutation inside functions the
         config declares pure (thrasher/storm plan(), the mgr
         controllers' pure cores, the traffic generators)
    CL12 observability drift: counters incremented vs declared,
         tracepoint names vs KNOWN_TRACEPOINTS, health checks raised
         vs documented (and raise-without-clear), admin/mon command
         names sent vs dispatched vs ceph_cli word-forms, stage-name
         sets consistent between tracer, histograms, and docs
    CL13 resource lifecycle: the RESOURCE_PAIRS acquire/release table
         (throttle tickets, pool buffers, sentinel refs, provisional
         traces, threads, observers/commands, files) proved released
         on every path — leaks on raise/return, double releases,
         unjoined threads
    CL14 teardown ordering: start/stop symmetry on lifecycle classes —
         everything start() brings up stop() must bring down, in
         reverse order, raise-tolerant, with first-daemon-wins guards
         on process-wide singleton installs

Suppression layers, innermost first:

    # noqa: CL1            on the finding line (flake8-style; bare
                           ``# noqa`` suppresses every check)
    baseline.toml          pinned (code, path, ident) entries, each with a
                           mandatory human justification line

Findings carry a line-independent ``ident`` so baseline entries survive
unrelated edits; the line number is for humans.
"""
from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class Finding:
    code: str      # "CL1".."CL5"
    path: str      # posix path as scanned (relative when possible)
    line: int
    ident: str     # stable key within (code, path); baseline match key
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.code, self.path, self.ident)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}  [{self.ident}]"

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "ident": self.ident,
            "message": self.message,
        }


@dataclass
class ModuleInfo:
    """One parsed source file."""

    path: Path              # as given (used for display)
    rel: str                # posix path relative to its scan root
    modname: str            # dotted module path relative to the scan root
    tree: ast.Module
    lines: list[str]
    _nodes: list | None = field(default=None, repr=False)

    def topdir(self) -> str:
        """First path component under the scan root ('' for top level)."""
        return self.rel.split("/", 1)[0] if "/" in self.rel else ""

    def walk(self) -> list:
        """``ast.walk(self.tree)`` materialized once and shared: every
        checker that needs a flat view of the module iterates the same
        list instead of re-running the BFS generator per check family."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes


# -- shared single-parse cache ----------------------------------------------
# Scanned modules parse exactly once per collect_modules() call; the
# source-of-truth files the drift checkers read (options.py,
# failpoint.py, tracer.py) go through this cache so CL4/CL5/CL12 hand
# the SAME tree around instead of re-reading and re-parsing per family.
# Keyed by (path, mtime_ns, size) so edited fixtures re-parse while the
# repeated whole-package runs the test suite does stay cheap.

_PARSE_CACHE: dict[tuple[str, int, int], tuple[ast.Module, list[str]]] = {}


def parse_source(path) -> tuple[ast.Module, list[str]]:
    """Parse-once (tree, lines) for a source file; raises BaselineError
    on unreadable/unparsable input like collect_modules does."""
    p = Path(path)
    try:
        st = p.stat()
        key = (str(p.resolve()), st.st_mtime_ns, st.st_size)
        hit = _PARSE_CACHE.get(key)
        if hit is not None:
            return hit
        src = p.read_text()
        tree = ast.parse(src, filename=str(p))
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        raise BaselineError(f"cannot parse {path}: {e}") from e
    out = (tree, src.splitlines())
    if len(_PARSE_CACHE) > 4096:  # fixture churn guard, not a hot limit
        _PARSE_CACHE.clear()
    _PARSE_CACHE[key] = out
    return out


_TEXT_CACHE: dict[tuple[str, int, int], str] = {}


def read_doc(path) -> str:
    """Read-once text for the docs files the drift checkers reconcile
    against (fault_injection.md, observability.md, tracing.md)."""
    p = Path(path)
    try:
        st = p.stat()
        key = (str(p.resolve()), st.st_mtime_ns, st.st_size)
        hit = _TEXT_CACHE.get(key)
        if hit is not None:
            return hit
        text = p.read_text()
    except (UnicodeDecodeError, OSError) as e:
        raise BaselineError(f"cannot read {path}: {e}") from e
    if len(_TEXT_CACHE) > 4096:
        _TEXT_CACHE.clear()
    _TEXT_CACHE[key] = text
    return text


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*))?",
                      re.IGNORECASE)


def noqa_codes(line: str) -> set[str] | None:
    """None = no noqa on this line; empty set = bare noqa (suppress all);
    otherwise the set of codes listed."""
    m = _NOQA_RE.search(line)
    if not m:
        return None
    codes = m.group("codes")
    if not codes:
        return set()
    return {c.strip().upper() for c in codes.split(",")}


def suppressed_by_noqa(f: Finding, mod: ModuleInfo) -> bool:
    if not (1 <= f.line <= len(mod.lines)):
        return False
    codes = noqa_codes(mod.lines[f.line - 1])
    if codes is None:
        return False
    return not codes or f.code in codes


# -- baseline (restricted TOML: [[suppress]] blocks of string keys) --------
# Python 3.10 has no tomllib and the container must not grow deps, so the
# baseline sticks to a subset a 30-line parser reads exactly: comment
# lines, ``[[suppress]]`` headers, and ``key = "value"`` string pairs.

class BaselineError(ValueError):
    pass


_KV_RE = re.compile(r'^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$')


def parse_baseline(text: str, where: str = "baseline.toml") -> list[dict]:
    entries: list[dict] = []
    cur: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppress]]":
            cur = {}
            entries.append(cur)
            continue
        m = _KV_RE.match(line)
        if not m:
            raise BaselineError(f"{where}:{lineno}: expected [[suppress]] or "
                                f'key = "value", got {line!r}')
        if cur is None:
            raise BaselineError(f"{where}:{lineno}: key outside [[suppress]]")
        cur[m.group(1)] = m.group(2).replace('\\"', '"').replace("\\\\", "\\")
    for i, e in enumerate(entries, 1):
        for k in ("code", "path", "ident", "reason"):
            if not e.get(k):
                raise BaselineError(
                    f"{where}: entry {i} missing {k!r} (a justification "
                    f"'reason' is mandatory)")
    return entries


def _toml_quote(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def format_baseline(findings: list[Finding], reason: str) -> str:
    out = ["# cephlint pinned baseline — regenerate with --write-baseline,",
           "# then EDIT each entry's reason into a real justification.", ""]
    for f in sorted(findings, key=lambda f: (f.code, f.path, f.ident)):
        out += ["[[suppress]]",
                f"code = {_toml_quote(f.code)}",
                f"path = {_toml_quote(f.path)}",
                f"ident = {_toml_quote(f.ident)}",
                f"reason = {_toml_quote(reason)}",
                ""]
    return "\n".join(out)


# -- configuration ----------------------------------------------------------
@dataclass
class Config:
    roots: list[Path]
    package_dir: Path | None = None
    docs_fault_injection: Path | None = None
    options_file: Path | None = None
    failpoint_file: Path | None = None
    baseline_file: Path | None = None
    use_baseline: bool = True
    #: CL12 source-of-truth files (tracer catalogue + observability docs)
    tracer_file: Path | None = None
    docs_observability: Path | None = None
    docs_tracing: Path | None = None
    checks: tuple[str, ...] = ("CL1", "CL2", "CL3", "CL4", "CL5",
                               "CL6", "CL7", "CL8", "CL9", "CL10",
                               "CL11", "CL12", "CL13", "CL14")
    cl3_dirs: tuple[str, ...] = ("ops", "crush", "parallel", "bench")
    cl1_raw_lock_dirs: tuple[str, ...] = ("osd", "mon", "msg", "store",
                                          "client", "common")
    cl8_dirs: tuple[str, ...] = ("ops", "gf", "crush")
    #: op-path files the CL8 host-trip AUDIT additionally covers (module
    #: scope, not just traced bodies): every host materialization of a
    #: device result / explicit transfer must be a deliberate, noqa'd
    #: sync point (the cephdma drive-to-zero contract; cl8_dirs modules
    #: are audited too)
    cl8_hostcopy_files: tuple[str, ...] = ("osd/write_batcher.py",
                                           "osd/ec_backend.py",
                                           "osd/read_batcher.py")
    #: the ONE module where ambient topology probes are legal (cephtopo:
    #: everything else receives a constructor-injected DevicePolicy)
    cl9_policy_modules: tuple[str, ...] = ("common/device_policy.py",)
    #: dirs whose PUBLIC module-level jitted names CL9 flags (jit entry
    #: points there must stay behind the telemetry/policy dispatch seam)
    cl9_jit_dirs: tuple[str, ...] = ("ops",)
    #: dirs the CL10 placement lattice walks (where sharding specs live)
    cl10_dirs: tuple[str, ...] = ("parallel", "ops")
    #: files/dirs under the seeded-determinism contract (CL11): the
    #: thrasher/storm planners, the race scheduler, the traffic
    #: generators, and the mgr controllers' pure cores.  Entries are
    #: rel-path prefixes; a .py entry matches that one file.
    cl11_plan_dirs: tuple[str, ...] = (
        "qa", "bench/traffic.py", "mgr/qos_module.py",
        "mgr/progress_module.py", "mgr/placement_module.py",
        "mgr/balancer_module.py", "osd/placement.py")
    #: functions declared PURE: same inputs => same outputs, no ambient
    #: clock/RNG anywhere on their call graph, no self/global mutation
    #: in their own body (deliberate fold-state writes carry noqa or a
    #: baseline entry).  "Class.method" for methods, bare name for
    #: module-level functions in cl11_plan_dirs modules.
    cl11_pure_roots: tuple[str, ...] = (
        "Thrasher.plan", "StormPlanner.plan", "QoSController.plan",
        "ProgressTracker.update", "cluster_report", "diff_mappings",
        "pool_skew", "skew_metrics", "tenant_next_op", "tenant_objects",
        "derive_rng")
    diff_files: frozenset[str] | None = None  # --diff: restrict findings

    @classmethod
    def discover(cls, roots: list[str | Path]) -> "Config":
        """Fill source-of-truth paths from the first scanned directory:
        <pkg>/common/options.py, <pkg>/common/failpoint.py,
        <repo>/docs/fault_injection.md, <pkg>/qa/analyzer/baseline.toml."""
        paths = [Path(r) for r in roots]
        cfg = cls(roots=paths)
        pkg = next((p for p in paths
                    if p.is_dir() and (p / "__init__.py").exists()), None)
        if pkg is None and paths and paths[0].is_dir():
            pkg = paths[0]
        if pkg is None:
            return cfg
        cfg.package_dir = pkg
        opt = pkg / "common" / "options.py"
        fp = pkg / "common" / "failpoint.py"
        tracer = pkg / "common" / "tracer.py"
        docs = pkg.resolve().parent / "docs" / "fault_injection.md"
        obs = pkg.resolve().parent / "docs" / "observability.md"
        trc = pkg.resolve().parent / "docs" / "tracing.md"
        base = pkg / "qa" / "analyzer" / "baseline.toml"
        cfg.options_file = opt if opt.exists() else None
        cfg.failpoint_file = fp if fp.exists() else None
        cfg.tracer_file = tracer if tracer.exists() else None
        cfg.docs_fault_injection = docs if docs.exists() else None
        cfg.docs_observability = obs if obs.exists() else None
        cfg.docs_tracing = trc if trc.exists() else None
        cfg.baseline_file = base if base.exists() else None
        return cfg


def rel_of(cfg: Config, path) -> str:
    """Scan-root-relative posix path for findings on source-of-truth
    files (options/failpoint/docs), so baseline entries stay portable
    across checkout locations.  Files outside every root (the docs live
    beside, not under, the package) relativize against the package's
    parent — the repo root in the shipped layout."""
    roots = list(cfg.roots)
    if cfg.package_dir is not None:
        roots.append(cfg.package_dir.resolve().parent)
    for root in roots:
        try:
            return path.resolve().relative_to(
                root.resolve() if root.is_dir() else root.parent.resolve()
            ).as_posix()
        except ValueError:
            continue
    return path.name


def collect_modules(cfg: Config) -> list[ModuleInfo]:
    mods: list[ModuleInfo] = []
    seen: set[Path] = set()
    for root in cfg.roots:
        if root.is_file():
            files = [(root, root.parent)]
        else:
            files = [(p, root) for p in sorted(root.rglob("*.py"))]
        for path, base in files:
            ap = path.resolve()
            if ap in seen:
                continue
            seen.add(ap)
            # an unparsable file is itself a finding-worthy event, but
            # the tier-1 gate wants determinism — surface it loudly
            tree, lines = parse_source(path)
            try:
                rel = path.resolve().relative_to(base.resolve()).as_posix()
            except ValueError:
                rel = path.name
            modname = rel[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            mods.append(ModuleInfo(path=path, rel=rel, modname=modname,
                                   tree=tree, lines=lines))
    return mods


@dataclass
class Report:
    findings: list[Finding]          # active (not noqa'd, not baselined)
    baselined: list[Finding] = field(default_factory=list)
    noqa: list[Finding] = field(default_factory=list)
    stale_baseline: list[dict] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "noqa": [f.to_json() for f in self.noqa],
            "stale_baseline": self.stale_baseline,
            "clean": self.clean,
        }

    def render_text(self) -> str:
        out = [f.render() for f in self.findings]
        if self.stale_baseline:
            out.append("")
            for e in self.stale_baseline:
                out.append(f"warning: stale baseline entry "
                           f"{e['code']} {e['path']} [{e['ident']}]")
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        summary = ", ".join(f"{c}: {n}" for c, n in sorted(counts.items()))
        out.append(
            f"cephlint: {len(self.findings)} finding(s)"
            + (f" ({summary})" if summary else "")
            + f", {len(self.baselined)} baselined, {len(self.noqa)} noqa'd")
        return "\n".join(out)


def run(cfg: Config) -> Report:
    from .symbols import SymbolTable
    from . import (cl1_locks, cl2_races, cl3_tracing, cl4_failpoints,
                   cl5_options, cl6_proto, cl7_errors, cl8_shapes,
                   cl9_topology, cl10_sharding, cl11_determinism,
                   cl12_obsdrift, cl13_lifecycle, cl14_teardown)

    mods = collect_modules(cfg)
    sym = SymbolTable.build(mods)
    checkers = {
        "CL1": cl1_locks.check,
        "CL2": cl2_races.check,
        "CL3": cl3_tracing.check,
        "CL4": cl4_failpoints.check,
        "CL5": cl5_options.check,
        "CL6": cl6_proto.check,
        "CL7": cl7_errors.check,
        "CL8": cl8_shapes.check,
        "CL9": cl9_topology.check,
        "CL10": cl10_sharding.check,
        "CL11": cl11_determinism.check,
        "CL12": cl12_obsdrift.check,
        "CL13": cl13_lifecycle.check,
        "CL14": cl14_teardown.check,
    }
    raw: list[Finding] = []
    for code in cfg.checks:
        raw.extend(checkers[code](mods, sym, cfg))
    raw.sort(key=lambda f: (f.path, f.line, f.code, f.ident))
    # de-dup identical (key, line) findings from overlapping walks
    uniq: dict[tuple, Finding] = {}
    for f in raw:
        uniq.setdefault((f.key(), f.line), f)
    raw = list(uniq.values())

    by_rel = {m.rel: m for m in mods}
    baseline = []
    if cfg.use_baseline and cfg.baseline_file and cfg.baseline_file.exists():
        baseline = parse_baseline(cfg.baseline_file.read_text(),
                                  str(cfg.baseline_file))
    base_keys = {(e["code"], e["path"], e["ident"]): e for e in baseline}

    report = Report(findings=[])
    hit_base: set[tuple] = set()
    for f in raw:
        mod = by_rel.get(f.path)
        if mod is not None and suppressed_by_noqa(f, mod):
            report.noqa.append(f)
            continue
        if f.key() in base_keys:
            hit_base.add(f.key())
            report.baselined.append(f)
            continue
        report.findings.append(f)
    # an entry for a check that didn't run is unjudged, not stale —
    # --checks CL6 must not condemn the CL5 baseline
    report.stale_baseline = [e for k, e in base_keys.items()
                             if k not in hit_base and e["code"] in cfg.checks]
    if cfg.diff_files is not None:
        # --diff mode: report only findings on the changed files.  The
        # ANALYSIS stays whole-package (cross-file checks need the full
        # symbol table); only the report narrows.  Stale-baseline
        # warnings are suppressed too — a partial view can't tell paid
        # debt from out-of-scope debt.
        keep = cfg.diff_files
        report.findings = [f for f in report.findings if f.path in keep]
        report.baselined = [f for f in report.baselined if f.path in keep]
        report.noqa = [f for f in report.noqa if f.path in keep]
        report.stale_baseline = []
    return report


_SARIF_RULES = {
    "CL1": "lock discipline (order inversions, blocking under a lock, "
           "raw locks)",
    "CL2": "unlocked read-modify-writes on shared state",
    "CL3": "JAX tracing hygiene",
    "CL4": "failpoint site/catalogue/docs drift",
    "CL5": "config-option read/declaration drift",
    "CL6": "wire-protocol conformance (encode/decode pairing, field "
           "loss, MSG_TYPE collisions, dispatch reachability)",
    "CL7": "error paths (swallowed exceptions, unbounded waits, "
           "unlocked reset handlers)",
    "CL8": "kernel shape/dtype dataflow",
    "CL9": "device-topology discipline (ambient devices/Mesh/backend "
           "probes outside the policy module, device-index literals, "
           "public jit entry points, pool-less donation)",
    "CL10": "sharding propagation (implicit reshards, contractions "
            "over a partitioned dim, sharded host trips, "
            "donation/out_shardings alias mismatches)",
    "CL11": "seeded determinism / purity (ambient RNG, wall-clock "
            "reads on the pure-plan call graph, unordered-collection "
            "iteration on the plan path, self/global mutation in "
            "declared-pure functions)",
    "CL12": "observability drift (counters incremented vs declared, "
            "tracepoints vs KNOWN_TRACEPOINTS, health checks raised "
            "vs documented, command names sent vs dispatched, "
            "stage-name set consistency)",
    "CL13": "resource lifecycle (acquire/release pairs checked "
            "path-sensitively with exception edges: leak-on-raise, "
            "leak-on-return, double-release, release-unacquired, "
            "thread-unjoined)",
    "CL14": "teardown ordering (start/stop symmetry: stop-missing, "
            "stop-order inversions, stop-fragile unprotected steps, "
            "restart-unsafe singleton installs)",
    # dynamic findings (qa/race — cephrace shares this report machinery)
    "CR1": "data race (empty lockset + no happens-before edge)",
    "CR2": "deadlock (waits-for cycle closed at runtime)",
    "CR3": "lost wakeup (notify with no waiter, later wait timed out)",
}


def render_sarif(report: Report, uri_prefix: str = "",
                 tool: str = "cephlint",
                 info_uri: str = "docs/static_analysis.md") -> str:
    """SARIF 2.1.0 for CI annotation (GitHub code scanning et al.).

    `uri_prefix` rebases the scan-root-relative finding paths onto the
    consumer's root (code-scanning resolves URIs against the REPO root,
    so a repo-root CLI run passes e.g. ``ceph_tpu/``)."""
    rules = sorted({f.code for f in report.findings})
    doc = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "informationUri": info_uri,
                "rules": [{"id": c,
                           "shortDescription":
                               {"text": _SARIF_RULES.get(c, c)}}
                          for c in rules],
            }},
            "results": [{
                "ruleId": f.code,
                "level": "error",
                "message": {"text": f"{f.message}  [{f.ident}]"},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": uri_prefix + f.path},
                        "region": {"startLine": max(f.line, 1)},
                    },
                }],
                "partialFingerprints": {
                    f"{tool}Ident": f"{f.code}:{f.path}:{f.ident}",
                },
            } for f in report.findings],
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def render(report: Report, fmt: str = "text", sarif_prefix: str = "",
           tool: str = "cephlint",
           info_uri: str = "docs/static_analysis.md") -> str:
    if fmt == "json":
        return json.dumps(report.to_json(), indent=2, sort_keys=True)
    if fmt == "sarif":
        return render_sarif(report, sarif_prefix, tool, info_uri)
    return report.render_text()
