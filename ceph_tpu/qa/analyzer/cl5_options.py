"""CL5 — config-option drift.

The option table (common/options.py, ``Option("name", ...)`` entries) and
the code that reads it (``<conf>.get("name")`` / ``get_expanded`` /
``conf["name"]``) must agree:

- ``read:<name>``    a literal read of an undeclared option — Config.get
  raises ConfigError at runtime, but only on the code path that reads it
  (exactly how dead tunables ship);
- ``unread:<name>``  a declared option nothing in the package reads —
  operators set it, nothing happens (the `osd_debug_*` rot shape the
  failpoint migration cleaned up).

Dynamically composed reads (``conf.get(f"debug_{subsys}")``) are handled
by prefix: any f-string/startswith prefix ending in ``_`` seen anywhere
in the package marks every declared option with that prefix as read.
Options that exist for operators/tests rather than package-internal
readers carry a baseline entry saying so.

The declaration list is parsed from the options file's AST, so fixture
trees analyze without being imported.
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo, parse_source, rel_of
from .symbols import SymbolTable


def parse_declared_options(path) -> dict[str, int]:
    """name -> declaration line for every Option("name", ...) literal."""
    tree, _lines = parse_source(path)
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "Option" and node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                out.setdefault(a0.value, node.lineno)
    return out


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    if cfg.options_file is None:
        return []
    declared = parse_declared_options(cfg.options_file)
    opt_rel = rel_of(cfg, cfg.options_file)

    findings: list[Finding] = []
    read_names: set[str] = set()
    for r in sym.option_reads:
        read_names.add(r.name)
        if r.name not in declared:
            findings.append(Finding(
                "CL5", r.path, r.line, f"read:{r.name}",
                f"config read of undeclared option {r.name!r} — "
                f"Config.get will raise ConfigError on this path; "
                f"declare it in common/options.py"))

    # a declared option also counts as read when any OTHER module mentions
    # its name as a bare string constant in a non-read position (command
    # tables, legacy-option maps, observer name lists); the declaration
    # file itself obviously mentions every name and proves nothing
    mentioned: set[str] = set()
    for rel, lits in sym.string_literals.items():
        if rel == opt_rel:
            continue
        mentioned |= lits & declared.keys()

    for name, line in sorted(declared.items()):
        if name in read_names or name in mentioned:
            continue
        if any(name.startswith(p) for p in sym.fstring_prefixes):
            continue  # dynamically composed read (f"debug_{subsys}")
        findings.append(Finding(
            "CL5", opt_rel, line, f"unread:{name}",
            f"option {name!r} is declared but nothing in the package "
            f"reads it — remove it or wire it up"))
    return findings
