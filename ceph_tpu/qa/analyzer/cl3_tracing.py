"""CL3 — JAX tracing hygiene in the accelerator dirs (ops/, crush/,
parallel/, bench/).

Functions that run under a trace — ``@jax.jit`` / ``@partial(jax.jit,
static_argnames=...)`` decorated defs, defs wrapped by a same-module
``jax.jit(fn)`` call, and kernels handed to ``pl.pallas_call`` — see
abstract tracers, not values.  Five host-side habits silently break or
degrade them, and every one has already bitten a TPU numerics stack
(PERF.md r1: the int64 leak; "Accelerating XOR-based Erasure Coding..."
shows the kernel win disappearing under host-side regressions):

- ``branch``: a Python ``if``/``while`` on a tracer-derived value —
  ConcretizationTypeError at trace time, or worse, a silently
  specialized constant.  Use jnp.where / lax.cond / lax.select.
- ``coerce``: ``bool()/int()/float()`` or ``.item()/.tolist()`` on a
  tracer — forces a device sync at best, trace error at worst.
- ``numpy``: ``np.*`` calls fed a tracer fall back to host numpy
  (ConcretizationTypeError or a silent device->host copy);
  use jnp.* inside traced code.
- ``promote``: explicitly casting the two sides of one arithmetic op to
  int32 vs uint32 — the promotion result flips with jax_enable_x64 and
  the CRUSH/GF hot paths depend on exact 32-bit wrap semantics.
- ``shape-loop``: a Python ``for`` over ``range(x.shape[i])`` /
  ``range(len(x))`` unrolls at trace time and recompiles per shape;
  hot paths want lax.fori_loop / lax.scan (a deliberate small unroll
  carries a ``# noqa: CL3`` with the bound).

Taint is tracked conservatively from the non-static parameters through
simple assignments; ``.shape``/``.dtype``/``.ndim``/``len()`` launder a
value back to static, so ``n = x.shape[0]; for i in range(n)`` is still
(only) a shape-loop, never a branch finding.
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, attr_chain, call_name

_JIT_NAMES = {"jit"}
_PALLAS_CALL = "pallas_call"
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize"}
_COERCERS = {"bool", "int", "float", "complex"}
_ITEM_METHODS = {"item", "tolist", "__bool__", "__float__", "__int__"}
_NUMPY_RECEIVERS = {"np", "numpy", "onp"}
_I32_CASTS = {"int32"}
_U32_CASTS = {"uint32"}


def _is_jit_expr(node: ast.expr) -> bool:
    """jax.jit / jit (bare reference, not a call)."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    return False


def _static_names_from_call(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
    return out


def _jit_decoration(fn: ast.FunctionDef) -> tuple[bool, set[str]]:
    """(is traced, static arg names) from the decorator list."""
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return True, set()
        if isinstance(dec, ast.Call):
            # @jax.jit(...) applied directly, or @partial(jax.jit, ...)
            if _is_jit_expr(dec.func):
                return True, _static_names_from_call(dec)
            if call_name(dec) == "partial" and dec.args \
                    and _is_jit_expr(dec.args[0]):
                return True, _static_names_from_call(dec)
    return False, set()


def _collect_traced(mod: ModuleInfo) -> list[tuple[ast.FunctionDef, set[str], str]]:
    """All (fn, static_names, why) functions in this module that run under
    a trace: decorated, jit-wrapped by name, or passed to pl.pallas_call."""
    defs: dict[str, ast.FunctionDef] = {}
    for node in mod.walk():
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)
    out: list[tuple[ast.FunctionDef, set[str], str]] = []
    claimed: set[str] = set()
    for name, fn in defs.items():
        jitted, static = _jit_decoration(fn)
        if jitted:
            out.append((fn, static, "jit"))
            claimed.add(name)
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn in _JIT_NAMES and _is_jit_expr(node.func) and node.args \
                and isinstance(node.args[0], ast.Name):
            tgt = node.args[0].id
            if tgt in defs and tgt not in claimed:
                out.append((defs[tgt], _static_names_from_call(node), "jit"))
                claimed.add(tgt)
        elif cn == _PALLAS_CALL and node.args \
                and isinstance(node.args[0], ast.Name):
            tgt = node.args[0].id
            if tgt in defs and tgt not in claimed:
                out.append((defs[tgt], set(), "pallas"))
                claimed.add(tgt)
    return out


# public alias: CL8's abstract interpreter analyzes the same traced-
# function population this check discovers
collect_traced = _collect_traced


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    dirs = set(cfg.cl3_dirs)
    for mod in mods:
        if mod.topdir() not in dirs:
            continue
        for fn, static, why in _collect_traced(mod):
            v = _TraceVisitor(mod, fn, static, why)
            v.run()
            findings.extend(v.findings)
    return findings


class _TraceVisitor:
    def __init__(self, mod: ModuleInfo, fn: ast.FunctionDef,
                 static: set[str], why: str):
        self.mod = mod
        self.fn = fn
        self.why = why
        args = fn.args
        names = [a.arg for a in
                 args.posonlyargs + args.args + args.kwonlyargs]
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                names.append(extra.arg)
        self.tainted: set[str] = {
            n for i, n in enumerate(names)
            if n not in static and str(i) not in static
            and n not in ("self", "cls")
        }
        self.findings: list[Finding] = []
        self._seen_idents: set[str] = set()

    # -- taint ------------------------------------------------------------
    def _traced(self, expr: ast.expr) -> bool:
        """Does this expression carry a tracer?  .shape/.dtype/len() and
        friends launder back to static."""
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self._traced(expr.value)
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Call):
            cn = call_name(expr)
            if cn == "len" or cn == "range":
                return any(self._traced(a) for a in expr.args)
            # a call is traced if any argument (or a traced receiver) is
            recv_traced = False
            if isinstance(expr.func, ast.Attribute):
                recv_traced = self._traced(expr.func.value)
            return recv_traced or any(self._traced(a) for a in expr.args) \
                or any(self._traced(kw.value) for kw in expr.keywords)
        if isinstance(expr, ast.Subscript):
            return self._traced(expr.value)
        if isinstance(expr, (ast.BinOp,)):
            return self._traced(expr.left) or self._traced(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self._traced(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self._traced(v) for v in expr.values)
        if isinstance(expr, ast.Compare):
            return self._traced(expr.left) \
                or any(self._traced(c) for c in expr.comparators)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._traced(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return any(self._traced(e)
                       for e in (expr.test, expr.body, expr.orelse))
        if isinstance(expr, ast.Starred):
            return self._traced(expr.value)
        return False

    def _taint_target(self, t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)

    # -- walk -------------------------------------------------------------
    def run(self) -> None:
        self._visit_body(self.fn.body)

    def _visit_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            if self._traced(stmt.value):
                for t in stmt.targets:
                    self._taint_target(t)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None and self._traced(stmt.value):
                self._taint_target(stmt.target)
        elif isinstance(stmt, (ast.If, ast.While)):
            if self._traced(stmt.test) and not self._none_test(stmt.test):
                kw = "while" if isinstance(stmt, ast.While) else "if"
                self._report(stmt.test, "branch",
                             f"Python {kw} on a tracer-derived value "
                             f"(use jnp.where / lax.cond / lax.select)")
        elif isinstance(stmt, ast.For):
            self._check_for(stmt)
        elif isinstance(stmt, ast.Assert):
            # assert on a tracer concretizes exactly like `if`
            if self._traced(stmt.test):
                self._report(stmt.test, "branch",
                             "assert on a tracer-derived value "
                             "(use checkify or move the check host-side)")
        for node in ast.iter_child_nodes(stmt):
            self._visit_node(node)

    def _visit_node(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._check_call(node)
        if isinstance(node, ast.BinOp):
            self._check_promotion(node)
        if isinstance(node, ast.stmt):
            self._visit_stmt(node)
            return
        for child in ast.iter_child_nodes(node):
            self._visit_node(child)

    @staticmethod
    def _none_test(test: ast.expr) -> bool:
        """`x is None` / `x is not None` style tests are static dispatch
        on an optional argument, not a tracer branch."""
        if isinstance(test, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops):
            return True
        return False

    # -- the five hazards --------------------------------------------------
    def _check_for(self, stmt: ast.For) -> None:
        it = stmt.iter
        if self._traced(it):
            self._report(it, "branch",
                         "Python for over a tracer (iterating a traced "
                         "array concretizes it; use lax.scan/fori_loop)")
            return
        # range(x.shape[0]) / range(len(x)): static, but unrolled —
        # recompiles per shape and bloats the HLO on big axes
        if isinstance(it, ast.Call) and call_name(it) == "range":
            for a in it.args:
                if self._shape_derived(a):
                    self._report(
                        it, "shape-loop",
                        "Python loop over a shape-derived range unrolls "
                        "at trace time and recompiles per shape (use "
                        "lax.fori_loop/scan, or # noqa: CL3 a deliberate "
                        "small unroll)")
                    return

    def _shape_derived(self, expr: ast.expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr == "shape" \
                    and self._mentions_tainted(node.value):
                return True
            if isinstance(node, ast.Call) and call_name(node) == "len" \
                    and node.args and self._mentions_tainted(node.args[0]):
                return True
        return False

    def _mentions_tainted(self, expr: ast.expr) -> bool:
        return any(isinstance(n, ast.Name) and n.id in self.tainted
                   for n in ast.walk(expr))

    def _check_call(self, node: ast.Call) -> None:
        f = node.func
        cn = call_name(node)
        # bool(x)/int(x)/float(x) on a tracer
        if isinstance(f, ast.Name) and cn in _COERCERS and node.args \
                and self._traced(node.args[0]):
            self._report(node, "coerce",
                         f"{cn}() concretizes a tracer (host sync / "
                         f"ConcretizationTypeError)")
        # x.item() / x.tolist()
        if isinstance(f, ast.Attribute) and f.attr in _ITEM_METHODS \
                and self._traced(f.value):
            self._report(node, "coerce",
                         f".{f.attr}() concretizes a tracer (host sync / "
                         f"ConcretizationTypeError)")
        # np.foo(tracer)
        if isinstance(f, ast.Attribute):
            ch = attr_chain(f)
            if ch and ch[0] in _NUMPY_RECEIVERS and (
                    any(self._traced(a) for a in node.args)
                    or any(self._traced(kw.value) for kw in node.keywords)):
                self._report(node, "numpy",
                             f"host numpy call {ch[0]}.{f.attr}(...) on a "
                             f"tracer (use jnp.{f.attr} inside traced code)")

    def _check_promotion(self, node: ast.BinOp) -> None:
        ls, rs = self._cast_sign(node.left), self._cast_sign(node.right)
        if ls and rs and ls != rs:
            self._report(
                node, "promote",
                "mixing explicit int32 and uint32 casts in one arithmetic "
                "op — the promoted dtype flips with jax_enable_x64 and "
                "breaks 32-bit wrap semantics in the CRUSH/GF hot path")

    @staticmethod
    def _cast_sign(expr: ast.expr) -> str | None:
        """'i32' / 'u32' when the expression is an explicit 32-bit int
        cast: jnp.int32(x), x.astype(jnp.uint32), np.uint32(x)."""
        if not isinstance(expr, ast.Call):
            return None
        f = expr.func
        name = None
        if isinstance(f, ast.Attribute) and f.attr == "astype" and expr.args:
            a = expr.args[0]
            ach = attr_chain(a)
            if ach and ach[1]:
                name = ach[1][-1]
            elif isinstance(a, ast.Constant) and isinstance(a.value, str):
                name = a.value
        else:
            cn = call_name(expr)
            if cn in _I32_CASTS | _U32_CASTS:
                name = cn
        if name in _I32_CASTS:
            return "i32"
        if name in _U32_CASTS:
            return "u32"
        return None

    def _report(self, node: ast.AST, kind: str, msg: str) -> None:
        ident = f"{self.fn.name}:{kind}"
        n = 2
        while ident in self._seen_idents:
            ident = f"{self.fn.name}:{kind}:{n}"
            n += 1
        self._seen_idents.add(ident)
        self.findings.append(Finding(
            "CL3", self.mod.rel, getattr(node, "lineno", self.fn.lineno),
            ident,
            f"[{self.why}:{self.fn.name}] {msg}"))
