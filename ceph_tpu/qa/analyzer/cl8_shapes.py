"""CL8 — kernel shape/dtype abstract interpreter for the TPU dirs
(ops/, gf/, crush/).

Shape and dtype mismatches in jitted/Pallas code only surface at trace
time — on the TPU, often behind the codec registry, long after the edit
that broke them — and the GF(2^8) paths additionally depend on EXACT
integer semantics (a silent int->float promotion produces bytes that are
almost right, the worst kind of wrong; arXiv:2108.02692 and the
polynomial-RS realizations in arXiv:1312.5155 both catalogue this class).
The interpreter propagates a small ``(shape, dtype)`` lattice through
every function CL3 identifies as traced (``@jax.jit``, same-module
``jax.jit(fn)``, ``pl.pallas_call`` kernels), seeded by literal
constructors (``jnp.zeros((8, 16), jnp.uint8)``), casts, and reshapes.
Unknown stays unknown — parameters have no static shape, so real
kernels mostly flow Top and the checker only speaks when BOTH sides of
a conflict are provably known:

- ``matmul:*``     contraction-dim mismatch in ``a @ b`` / ``jnp.dot``/
  ``jnp.matmul`` (and literal ``dimension_numbers`` of
  ``lax.dot_general``);
- ``broadcast:*``  an elementwise binop whose known dims can't
  broadcast (unequal, neither 1);
- ``reshape:*``    a reshape whose literal target element count differs
  from the known source count;
- ``promote:*``    arithmetic mixing a concrete int array with a
  concrete float array — the implicit promotion silently leaves the
  GF(2^8)/CRUSH integer domain (explicit ``astype`` is the idiom);
- ``int-div:*``    true division ``/`` on integer arrays — the result
  is float even when both sides are int (use ``//`` or cast first);
- ``host-trip:*``  ``jax.device_get``/``device_put``/
  ``block_until_ready`` inside a traced body — a host<->device round
  trip per trace (or a trace error), never what a kernel wants.

cephdma adds the op-path HOST-TRIP AUDIT on top (``hosttrip:*``
idents): every function — traced or not — in the ``cl8_dirs`` modules
plus ``cl8_hostcopy_files`` (osd/write_batcher.py, osd/ec_backend.py)
is scanned for explicit host<->device traffic: ``jax.device_get`` /
``jax.device_put`` / ``.block_until_ready()`` calls, and
``np.asarray``/``np.array`` wrapped directly around a device-producing
kernel entry point (``apply_matrix_jax`` and friends — the
materialize-at-the-callsite idiom the device pool exists to kill).
The contract is drive-to-zero: a deliberate sync or transfer seam (the
pool's own ``device_put``, an op's commit-point fetch, the pool-off
historical flush) carries an explicit ``# noqa: CL8`` with its reason;
everything else is a finding.  Baseline growth is a regression.

Weak-typed Python scalars adopt the array side's dtype (JAX semantics)
and never report.  ``# noqa: CL8`` / baseline.toml suppress as usual.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, attr_chain, call_name

_INT_DTYPES = {"int8", "int16", "int32", "int64",
               "uint8", "uint16", "uint32", "uint64"}
_FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16"}
_DTYPE_NAMES = _INT_DTYPES | _FLOAT_DTYPES | {"bool", "bool_"}
_CTOR_DEFAULT_FLOAT = {"zeros", "ones", "empty", "full", "eye", "linspace"}
_LIKE_CTORS = {"zeros_like", "ones_like", "empty_like", "full_like"}
_HOST_TRIPS = {"device_get", "device_put", "block_until_ready"}
_MODULE_ALIASES = {"jnp", "np", "numpy", "onp", "jax", "lax", "pl"}
_ARITH = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
          ast.Pow)


@dataclass(frozen=True)
class AV:
    """Abstract value: shape is a tuple of (int | None) dims or None for
    wholly unknown; dtype is a dtype name or None; weak marks Python
    scalars (they adopt the other operand's dtype, JAX-style)."""
    shape: tuple | None = None
    dtype: str | None = None
    weak: bool = False


TOP = AV()


def _is_int(dt: str | None) -> bool:
    return dt in _INT_DTYPES


def _is_float(dt: str | None) -> bool:
    return dt in _FLOAT_DTYPES


def _dtype_of_node(node: ast.expr | None) -> str | None:
    """jnp.uint8 / np.float32 / "uint8" -> dtype name."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    ch = attr_chain(node)
    if ch:
        leaf = ch[1][-1] if ch[1] else ch[0]
        return leaf if leaf in _DTYPE_NAMES else None
    return None


def _const_int(node: ast.expr) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        l, r = _const_int(node.left), _const_int(node.right)
        if l is None or r is None:
            return None
        if isinstance(node.op, ast.Mult):
            return l * r
        if isinstance(node.op, ast.Add):
            return l + r
        if isinstance(node.op, ast.Sub):
            return l - r
    return None


def _const_shape(node: ast.expr) -> tuple | None:
    """Literal shape argument: (8, 16) -> (8, 16); 8 -> (8,); dims that
    aren't literal ints become None (unknown dim)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_const_int(e) for e in node.elts)
    v = _const_int(node)
    if v is not None:
        return (v,)
    return None


def _broadcast(a: tuple | None, b: tuple | None):
    """(result_shape, conflict_dim_pair | None) under numpy rules."""
    if a is None or b is None:
        return None, None
    out = []
    for i in range(1, max(len(a), len(b)) + 1):
        da = a[-i] if i <= len(a) else 1
        db = b[-i] if i <= len(b) else 1
        if da is None or db is None:
            out.append(None)
        elif da == db or db == 1:
            out.append(da)
        elif da == 1:
            out.append(db)
        else:
            return None, (da, db)
    return tuple(reversed(out)), None


#: device-producing kernel entry points: np.asarray(<one of these>(...))
#: is a host materialization of a device result at the callsite
_DEVICE_PRODUCERS = {
    "apply_matrix_jax", "apply_xor_matrix_jax", "apply_matrix_dev",
    "apply_xor_matrix_dev", "apply_matrix_xla", "apply_matrix_pallas",
    "_apply_bitmatrix", "_apply_bitmatrix_donated",
}
_MATERIALIZERS = {"asarray", "array"}


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    from .cl3_tracing import collect_traced

    findings: list[Finding] = []
    dirs = set(cfg.cl8_dirs)
    audit_files = set(getattr(cfg, "cl8_hostcopy_files", ()))
    for mod in mods:
        in_dirs = mod.topdir() in dirs
        in_audit = in_dirs or mod.rel in audit_files
        if not in_audit:
            continue
        traced_fns = set()
        if in_dirs:
            for fn, _static, why in collect_traced(mod):
                traced_fns.add(fn)
                interp = _Interp(mod, fn, why)
                interp.run()
                findings.extend(interp.findings)
        findings.extend(_audit_host_trips(mod, traced_fns))
    return findings


def _audit_host_trips(mod: ModuleInfo, traced_fns: set) -> list[Finding]:
    """The cephdma op-path audit (module docstring): explicit
    host<->device traffic outside traced bodies must be a noqa'd
    deliberate seam.  Traced functions are skipped — the interpreter
    above already polices those with the stricter in-trace rule."""
    findings: list[Finding] = []
    seen: set[str] = set()

    def report(node: ast.AST, owner: str, msg: str) -> None:
        ident = f"hosttrip:{owner}"
        n = 2
        while ident in seen:
            ident = f"hosttrip:{owner}:{n}"
            n += 1
        seen.add(ident)
        findings.append(Finding(
            "CL8", mod.rel, getattr(node, "lineno", 1), ident, msg))

    def own_nodes(scope):
        """`scope`'s statements WITHOUT descending into nested
        functions — those are walked (and attributed) on their own."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def audit_scope(scope, owner: str) -> None:
        for node in own_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn in ("device_get", "device_put", "block_until_ready"):
                report(node, f"{owner}:{cn}",
                       f"[{owner}] explicit host<->device traffic "
                       f"({cn}) on the op path — route through the "
                       f"device pool / async seams, or mark the "
                       f"deliberate sync with a reasoned noqa")
                continue
            if cn in _MATERIALIZERS and node.args \
                    and isinstance(node.args[0], ast.Call):
                inner = call_name(node.args[0])
                if inner in _DEVICE_PRODUCERS:
                    report(node, f"{owner}:{cn}({inner})",
                           f"[{owner}] {cn}() materializes {inner}'s "
                           f"device result at the callsite — a "
                           f"host-copy sync per call; keep it "
                           f"device-resident (apply_matrix_dev + "
                           f"commit-point fetch) or noqa the "
                           f"deliberate sync")

    # module scope (import-time transfers count too) — own_nodes skips
    # every FunctionDef subtree, so functions are attributed below
    audit_scope(mod.tree, "<module>")
    for fn in mod.walk():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn in traced_fns:
            continue
        audit_scope(fn, fn.name)
    return findings


class _Interp:
    def __init__(self, mod: ModuleInfo, fn: ast.FunctionDef, why: str):
        self.mod = mod
        self.fn = fn
        self.why = why
        self.env: dict[str, AV] = {}
        self.findings: list[Finding] = []
        self._seen: set[str] = set()

    def run(self) -> None:
        self._body(self.fn.body)

    # -- statements --------------------------------------------------------
    def _body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            val = self._ev(stmt.value)
            for t in stmt.targets:
                self._bind(t, val)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self._ev(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            synth = ast.BinOp(left=stmt.target, op=stmt.op,
                              right=stmt.value)
            ast.copy_location(synth, stmt)
            ast.fix_missing_locations(synth)
            val = self._ev(synth)
            self._bind(stmt.target, val)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if stmt.value is not None:
                self._ev(stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._ev(stmt.test)
            self._body(stmt.body)
            self._body(stmt.orelse)
        elif isinstance(stmt, ast.For):
            self._ev(stmt.iter)
            self._bind(stmt.target, TOP)
            self._body(stmt.body)
            self._body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self._ev(item.context_expr)
            self._body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._body(stmt.body)
            for h in stmt.handlers:
                self._body(h.body)
            self._body(stmt.orelse)
            self._body(stmt.finalbody)
        elif isinstance(stmt, ast.FunctionDef):
            self._body(stmt.body)  # nested kernels see the outer env

    def _bind(self, target: ast.expr, val: AV) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, TOP)

    # -- expressions -------------------------------------------------------
    def _ev(self, expr: ast.expr) -> AV:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, TOP)
        if isinstance(expr, ast.Constant):
            v = expr.value
            if isinstance(v, bool):
                return AV((), "bool", weak=True)
            if isinstance(v, int):
                return AV((), "int32", weak=True)
            if isinstance(v, float):
                return AV((), "float32", weak=True)
            return TOP
        if isinstance(expr, ast.BinOp):
            return self._binop(expr)
        if isinstance(expr, ast.UnaryOp):
            return self._ev(expr.operand)
        if isinstance(expr, ast.Call):
            return self._call(expr)
        if isinstance(expr, ast.Attribute):
            if expr.attr == "T":
                base = self._ev(expr.value)
                if base.shape is not None:
                    return AV(tuple(reversed(base.shape)), base.dtype)
                return AV(None, base.dtype)
            # .shape/.dtype/.at and friends leave the lattice
            self._ev(expr.value)
            return TOP
        if isinstance(expr, ast.Subscript):
            base = self._ev(expr.value)
            if not isinstance(expr.slice, ast.Slice):
                self._ev_slicefree(expr.slice)
            # indexing reshapes unpredictably; keep only the dtype
            return AV(None, base.dtype)
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                self._ev(e)
            return TOP
        if isinstance(expr, ast.Compare):
            self._ev(expr.left)
            for c in expr.comparators:
                self._ev(c)
            ls = self._ev(expr.left).shape
            return AV(ls, "bool")
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                self._ev(v)
            return TOP
        if isinstance(expr, ast.IfExp):
            self._ev(expr.test)
            a, b = self._ev(expr.body), self._ev(expr.orelse)
            return a if a.shape is not None else b
        if isinstance(expr, ast.Starred):
            return self._ev(expr.value)
        return TOP

    def _ev_slicefree(self, node: ast.expr) -> None:
        if isinstance(node, ast.Tuple):
            for e in node.elts:
                if not isinstance(e, ast.Slice):
                    self._ev(e)
        elif not isinstance(node, ast.Slice):
            self._ev(node)

    # -- binops ------------------------------------------------------------
    def _binop(self, node: ast.BinOp) -> AV:
        l, r = self._ev(node.left), self._ev(node.right)
        if isinstance(node.op, ast.MatMult):
            return self._matmul(node, l, r)
        shape, conflict = _broadcast(l.shape, r.shape)
        if conflict is not None:
            self._report(node, "broadcast",
                         f"elementwise op broadcasts shapes {l.shape} and "
                         f"{r.shape}: dims {conflict[0]} vs {conflict[1]} "
                         f"are incompatible")
        dtype = self._promote(node, l, r)
        return AV(shape, dtype)

    def _promote(self, node: ast.BinOp, l: AV, r: AV) -> str | None:
        ld = None if l.weak else l.dtype
        rd = None if r.weak else r.dtype
        if isinstance(node.op, ast.Div):
            # only speak when the int domain is PROVEN: one side must be
            # a concrete int array, and the other int-kind too (a weak
            # Python int literal counts; an unknown side could be float,
            # where / is already correct)
            concrete_int = _is_int(ld) or _is_int(rd)
            both_intish = _is_int(l.dtype) and _is_int(r.dtype)
            if concrete_int and both_intish:
                self._report(
                    node, "int-div",
                    f"true division on integer arrays "
                    f"({ld or rd}) silently promotes to float — the "
                    f"GF(2^8)/CRUSH paths need // or an explicit astype")
                return "float32"
        if isinstance(node.op, _ARITH) and _is_int(ld) and _is_float(rd):
            self._report(node, "promote",
                         f"arithmetic mixes {ld} with {rd} — the int "
                         f"side is implicitly promoted to float and "
                         f"leaves the exact-integer domain; cast "
                         f"explicitly with astype")
            return rd
        if isinstance(node.op, _ARITH) and _is_float(ld) and _is_int(rd):
            self._report(node, "promote",
                         f"arithmetic mixes {ld} with {rd} — the int "
                         f"side is implicitly promoted to float and "
                         f"leaves the exact-integer domain; cast "
                         f"explicitly with astype")
            return ld
        if ld is None:
            return rd
        if rd is None:
            return ld
        if ld == rd:
            return ld
        return None

    def _matmul(self, node: ast.AST, l: AV, r: AV) -> AV:
        ls, rs = l.shape, r.shape
        if ls is not None and rs is not None and ls and rs:
            lk = ls[-1]
            rk = rs[-2] if len(rs) >= 2 else rs[0]
            if lk is not None and rk is not None and lk != rk:
                self._report(node, "matmul",
                             f"matmul contraction dims differ: "
                             f"{ls} @ {rs} contracts {lk} against {rk}")
            out = tuple(ls[:-1]) + (tuple(rs[:-2]) + (rs[-1],)
                                    if len(rs) >= 2 else ())
            dtype = l.dtype if l.dtype == r.dtype else None
            return AV(out, dtype)
        dtype = l.dtype if l.dtype == r.dtype else None
        return AV(None, dtype)

    # -- calls -------------------------------------------------------------
    def _call(self, node: ast.Call) -> AV:
        for a in node.args:
            self._ev(a)
        for kw in node.keywords:
            self._ev(kw.value)
        cn = call_name(node)
        f = node.func
        if cn in _HOST_TRIPS:
            self._report(node, "host-trip",
                         f"{cn} inside a traced body forces a "
                         f"host<->device round trip per call (or a trace "
                         f"error); keep kernels device-only")
            return TOP
        kwmap = {kw.arg: kw.value for kw in node.keywords if kw.arg}

        # dtype-constructor casts: jnp.uint8(x), np.int32(x)
        if cn in _DTYPE_NAMES and node.args:
            inner = self._ev(node.args[0])
            return AV(inner.shape, cn)

        # jnp.reshape / np.where / lax.dot_general are module FUNCTIONS,
        # not methods — route them past the method branch (whose receiver
        # eval would misparse the array argument as the shape)
        is_module_fn = isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Name) \
            and f.value.id in _MODULE_ALIASES
        if isinstance(f, ast.Attribute) and not is_module_fn:
            recv = self._ev(f.value)
            if cn == "astype" and node.args:
                dt = _dtype_of_node(node.args[0])
                return AV(recv.shape, dt or None)
            if cn == "reshape":
                return self._reshape(node, recv, node.args, kwmap)
            if cn == "transpose":
                if recv.shape is not None and not node.args:
                    return AV(tuple(reversed(recv.shape)), recv.dtype)
                return AV(None, recv.dtype)
            if cn in ("sum", "min", "max", "prod"):
                dt = _dtype_of_node(kwmap.get("dtype")) or recv.dtype
                return AV(None, dt)
            if cn == "mean":
                return AV(None, "float32")

        # module-level jnp/np constructors and transforms
        if cn in _CTOR_DEFAULT_FLOAT and node.args:
            shape = _const_shape(node.args[0]) if cn != "eye" else None
            if cn == "eye":
                n = _const_int(node.args[0])
                shape = (n, n) if n is not None else None
            dt = _dtype_of_node(kwmap.get("dtype"))
            if dt is None and cn == "full" and len(node.args) >= 3:
                dt = _dtype_of_node(node.args[2])
            elif dt is None and cn not in ("full",) and len(node.args) >= 2:
                dt = _dtype_of_node(node.args[1])
            return AV(shape, dt or "float32")
        if cn in _LIKE_CTORS and node.args:
            src = self._ev(node.args[0])
            dt = _dtype_of_node(kwmap.get("dtype")) or src.dtype
            return AV(src.shape, dt)
        if cn == "arange":
            n = _const_int(node.args[0]) if node.args else None
            dt = _dtype_of_node(kwmap.get("dtype")) or "int32"
            return AV((n,) if n is not None and len(node.args) == 1 else None,
                      dt)
        if cn in ("asarray", "array") and node.args:
            src = self._ev(node.args[0])
            dt = _dtype_of_node(kwmap.get("dtype"))
            if dt is None and len(node.args) >= 2:
                dt = _dtype_of_node(node.args[1])
            return AV(src.shape, dt or src.dtype)
        if cn == "reshape" and node.args:
            src = self._ev(node.args[0])
            return self._reshape(node, src, node.args[1:], kwmap)
        if cn == "where" and len(node.args) == 3:
            a, b = self._ev(node.args[1]), self._ev(node.args[2])
            shape, conflict = _broadcast(a.shape, b.shape)
            if conflict is not None:
                self._report(node, "broadcast",
                             f"where() branches have incompatible shapes "
                             f"{a.shape} vs {b.shape}")
            return AV(shape, a.dtype if a.dtype == b.dtype else None)
        if cn in ("dot", "matmul") and len(node.args) >= 2:
            return self._matmul(node, self._ev(node.args[0]),
                                self._ev(node.args[1]))
        if cn == "dot_general" and len(node.args) >= 2:
            return self._dot_general(node, kwmap)
        if cn == "stack" and node.args \
                and isinstance(node.args[0], (ast.List, ast.Tuple)):
            elts = [self._ev(e) for e in node.args[0].elts]
            if elts and all(e.shape == elts[0].shape and e.shape is not None
                            for e in elts):
                return AV((len(elts),) + elts[0].shape, elts[0].dtype)
            return TOP
        return TOP

    def _reshape(self, node: ast.AST, src: AV, args: list[ast.expr],
                 kwmap: dict) -> AV:
        if len(args) == 1:
            shape = _const_shape(args[0])
        else:
            shape = tuple(_const_int(a) for a in args) if args else None
        if shape is None:
            return AV(None, src.dtype)
        if src.shape is not None and all(d is not None for d in src.shape):
            src_n = 1
            for d in src.shape:
                src_n *= d
            knowns = [d for d in shape if d is not None and d != -1]
            tgt_n = 1
            for d in knowns:
                tgt_n *= d
            if all(d is not None for d in shape) and -1 not in shape:
                if tgt_n != src_n:
                    self._report(
                        node, "reshape",
                        f"reshape {src.shape} -> {shape}: element count "
                        f"{src_n} != {tgt_n}")
            elif -1 in shape and tgt_n and src_n % tgt_n:
                self._report(
                    node, "reshape",
                    f"reshape {src.shape} -> {shape}: {src_n} elements "
                    f"don't divide by the known dims ({tgt_n})")
        return AV(shape, src.dtype)

    def _dot_general(self, node: ast.Call, kwmap: dict) -> AV:
        l, r = self._ev(node.args[0]), self._ev(node.args[1])
        dn = kwmap.get("dimension_numbers")
        if len(node.args) >= 3 and dn is None:
            dn = node.args[2]
        pairs = _literal_dim_numbers(dn)
        if pairs is not None and l.shape is not None and r.shape is not None:
            for lc, rc in pairs:
                if lc < len(l.shape) and rc < len(r.shape):
                    dl, dr = l.shape[lc], r.shape[rc]
                    if dl is not None and dr is not None and dl != dr:
                        self._report(
                            node, "matmul",
                            f"dot_general contracts dim {lc} of "
                            f"{l.shape} ({dl}) against dim {rc} of "
                            f"{r.shape} ({dr})")
        dt = _dtype_of_node(kwmap.get("preferred_element_type"))
        return AV(None, dt)

    def _report(self, node: ast.AST, kind: str, msg: str) -> None:
        ident = f"{self.fn.name}:{kind}"
        n = 2
        while ident in self._seen:
            ident = f"{self.fn.name}:{kind}:{n}"
            n += 1
        self._seen.add(ident)
        self.findings.append(Finding(
            "CL8", self.mod.rel, getattr(node, "lineno", self.fn.lineno),
            ident, f"[{self.why}:{self.fn.name}] {msg}"))


def _literal_dim_numbers(node: ast.expr | None):
    """(((lc,), (rc,)), ((), ())) literal -> [(lc, rc), ...]; None when
    not a literal."""
    if not isinstance(node, ast.Tuple) or not node.elts:
        return None
    contract = node.elts[0]
    if not isinstance(contract, ast.Tuple) or len(contract.elts) != 2:
        return None
    lcs, rcs = contract.elts
    if not isinstance(lcs, ast.Tuple) or not isinstance(rcs, ast.Tuple):
        return None
    out = []
    for le, re_ in zip(lcs.elts, rcs.elts):
        lv, rv = _const_int(le), _const_int(re_)
        if lv is None or rv is None:
            return None
        out.append((lv, rv))
    return out
