"""cephlint cross-file symbol table.

One pass over every parsed module collects the facts the per-file
checkers need global views of:

- classes, their base names, their methods, and which classes form a
  "family" (a class plus every mixin/base combined into it — the OSD is
  ten mixins whose methods all share the locks OSD.__init__ creates);
- lock-valued instance attributes (threading.Lock/RLock/Condition,
  lockdep.make_lock/LockdepLock) with their lockdep names, plus
  module-level locks and @property aliases to another attribute's lock;
- instance-attribute types (``self.mc = MonClient(...)`` records mc ->
  MonClient) so ``with self.mc._lock`` and ``self.store.queue_transaction``
  resolve across files;
- failpoint site/arming literals, config-option read literals, every
  string constant, and f-string prefixes (for dynamically composed option
  names like ``f"debug_{subsys}"``).

Resolution is deliberately conservative: anything ambiguous resolves to
None and the checkers stay silent about it rather than guessing.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import ModuleInfo

LOCK_CTORS = {"Lock", "RLock"}
CONDITION_CTORS = {"Condition"}
NAMED_LOCK_CTORS = {"make_lock", "LockdepLock"}
_CONF_RECEIVERS = {"conf", "config", "_config", "cfg"}
_REGISTRY_NAMES = {"registry", "_registry", "fp_registry"}


def attr_chain(node: ast.expr) -> tuple[str, list[str]] | None:
    """``self._session.lock`` -> ("self", ["_session", "lock"]);
    ``NAME`` -> ("NAME", []).  None for anything else."""
    attrs: list[str] = []
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, list(reversed(attrs))
    return None


def call_name(node: ast.Call) -> str | None:
    """Rightmost name of the called thing: foo() -> foo, a.b.c() -> c."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


@dataclass
class LockInfo:
    attr: str                 # attribute or module-global name
    owner: str                # "module.Class" or "module"
    name: str                 # lockdep name (or derived pseudo-name)
    kind: str                 # "lock" | "rlock" | "named" | "condition"
    alias_chain: tuple[str, ...] | None = None  # Condition(self.X) -> ("X",)
    line: int = 0
    path: str = ""


@dataclass
class ClassInfo:
    module: str
    name: str
    bases: list[str]
    node: ast.ClassDef
    path: str
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    lock_attrs: dict[str, LockInfo] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)  # attr -> class name
    property_aliases: dict[str, tuple[str, ...]] = field(default_factory=dict)
    spawns_threads: bool = False

    @property
    def key(self) -> str:
        return f"{self.module}.{self.name}"


@dataclass
class FailpointSite:
    name: str
    kind: str      # "site" (marker in daemon code) | "arm" (set/add/remove)
    path: str
    line: int


@dataclass
class OptionRead:
    name: str
    path: str
    line: int


class SymbolTable:
    def __init__(self) -> None:
        self.classes: dict[str, ClassInfo] = {}
        self.class_by_name: dict[str, list[ClassInfo]] = {}
        self.module_locks: dict[tuple[str, str], LockInfo] = {}
        self.failpoint_sites: list[FailpointSite] = []
        self.option_reads: list[OptionRead] = []
        self.string_literals: dict[str, set[str]] = {}  # rel path -> set
        self.fstring_prefixes: set[str] = set()
        # inheritance edges by class key (built in build())
        self._parents: dict[str, set[str]] = {}
        self._children: dict[str, set[str]] = {}
        self._family_cache: dict[str, list[ClassInfo]] = {}
        # package-wide indexes (built in _finish)
        self.lock_attr_index: dict[str, list[LockInfo]] = {}
        self.attr_type_index: dict[str, set[str]] = {}

    # -- family: the classes that can share an instance ---------------------
    # A method of class C runs on instances of C's subclasses, so the
    # attributes it may touch are those set up anywhere along the
    # inheritance CHAIN through C: C's descendants plus every ancestor of
    # those descendants (the OSD is ten mixins whose methods all share
    # the locks OSD.__init__ creates).  Crucially this does NOT merge
    # siblings: two Dispatcher subclasses never share an instance, so
    # MDSDaemon._lock must not resolve into Objecter._lock.
    def _closure(self, key: str, edges: dict[str, set[str]]) -> set[str]:
        seen = {key}
        work = [key]
        while work:
            for nxt in edges.get(work.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
        return seen

    def family_members(self, cls: ClassInfo) -> list[ClassInfo]:
        cached = self._family_cache.get(cls.key)
        if cached is not None:
            return cached
        keys: set[str] = set()
        for desc in self._closure(cls.key, self._children):
            keys |= self._closure(desc, self._parents)
        members = [self.classes[k] for k in sorted(keys) if k in self.classes]
        self._family_cache[cls.key] = members
        return members

    def family_locks(self, cls: ClassInfo) -> dict[str, LockInfo]:
        out: dict[str, LockInfo] = {}
        for c in self.family_members(cls):
            for attr, li in c.lock_attrs.items():
                out.setdefault(attr, li)
        return out

    def family_attr_types(self, cls: ClassInfo) -> dict[str, str]:
        out: dict[str, str] = {}
        for c in self.family_members(cls):
            for attr, t in c.attr_types.items():
                out.setdefault(attr, t)
        return out

    def family_methods(self, cls: ClassInfo) -> dict[str, tuple[ClassInfo, ast.FunctionDef]]:
        out: dict[str, tuple[ClassInfo, ast.FunctionDef]] = {}
        for c in self.family_members(cls):
            for name, fn in c.methods.items():
                out.setdefault(name, (c, fn))
        return out

    def family_properties(self, cls: ClassInfo) -> dict[str, tuple[str, ...]]:
        out: dict[str, tuple[str, ...]] = {}
        for c in self.family_members(cls):
            for attr, chain in c.property_aliases.items():
                out.setdefault(attr, chain)
        return out

    def family_threaded(self, cls: ClassInfo) -> bool:
        members = self.family_members(cls)
        return any(c.spawns_threads for c in members) or any(
            c.lock_attrs for c in members
        )

    # -- build --------------------------------------------------------------
    @classmethod
    def build(cls, mods: list[ModuleInfo]) -> "SymbolTable":
        sym = cls()
        for mod in mods:
            sym._scan_module(mod)
        # inheritance edges to (package-local, name-matched) bases
        for ci in list(sym.classes.values()):
            for base in ci.bases:
                for other in sym.class_by_name.get(base, []):
                    if other.key != ci.key:
                        sym._parents.setdefault(ci.key, set()).add(other.key)
                        sym._children.setdefault(other.key, set()).add(ci.key)
        sym._finish()
        return sym

    def _finish(self) -> None:
        for ci in self.classes.values():
            for attr, li in ci.lock_attrs.items():
                self.lock_attr_index.setdefault(attr, []).append(li)
            for attr, t in ci.attr_types.items():
                self.attr_type_index.setdefault(attr, set()).add(t)

    def _scan_module(self, mod: ModuleInfo) -> None:
        lits = self.string_literals.setdefault(mod.rel, set())
        for node in mod.walk():
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                lits.add(node.value)
            elif isinstance(node, ast.JoinedStr):
                if node.values and isinstance(node.values[0], ast.Constant) \
                        and isinstance(node.values[0].value, str) \
                        and len(node.values) > 1:
                    prefix = node.values[0].value
                    if prefix.endswith("_"):
                        self.fstring_prefixes.add(prefix)
            elif isinstance(node, ast.Call):
                self._scan_call(mod, node)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and self._confish(node.value):
                self.option_reads.append(
                    OptionRead(node.slice.value, mod.rel, node.lineno))
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_class(mod, stmt)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                li = self._lock_from_call(stmt.value, mod.modname,
                                          stmt.targets[0].id, mod.rel)
                if li is not None:
                    self.module_locks[(mod.modname, stmt.targets[0].id)] = li

    def _scan_call(self, mod: ModuleInfo, node: ast.Call) -> None:
        name = call_name(node)
        arg0 = node.args[0] if node.args else None
        lit0 = arg0.value if (isinstance(arg0, ast.Constant)
                              and isinstance(arg0.value, str)) else None
        # failpoint sites: failpoint("..."), self._fp_hit("..."),
        # <registry>.hit/configured("..."), <registry>.set/add/remove("...")
        if lit0 is not None:
            if name == "failpoint" or name == "_fp_hit":
                self.failpoint_sites.append(
                    FailpointSite(lit0, "site", mod.rel, node.lineno))
            elif isinstance(node.func, ast.Attribute) and \
                    name in ("hit", "configured", "set", "add", "remove") \
                    and self._registryish(node.func.value):
                kind = "site" if name in ("hit", "configured") else "arm"
                self.failpoint_sites.append(
                    FailpointSite(lit0, kind, mod.rel, node.lineno))
        # config-option reads: <conf>.get("..."), <conf>.get_expanded("...")
        if lit0 is not None and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "get_expanded") \
                and self._confish(node.func.value):
            self.option_reads.append(OptionRead(lit0, mod.rel, node.lineno))
        # .startswith("x_") teaches CL5 a dynamic option-name prefix
        if name == "startswith" and lit0 is not None and lit0.endswith("_"):
            self.fstring_prefixes.add(lit0)

    @staticmethod
    def _confish(node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in _CONF_RECEIVERS
        if isinstance(node, ast.Attribute):
            return node.attr in _CONF_RECEIVERS
        return False

    @staticmethod
    def _registryish(node: ast.expr) -> bool:
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            return node.func.id in _REGISTRY_NAMES
        if isinstance(node, ast.Name):
            return node.id in _REGISTRY_NAMES
        chain = attr_chain(node)
        return bool(chain and chain[1] and chain[1][-1] in _REGISTRY_NAMES)

    # -- classes ------------------------------------------------------------
    def _scan_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            ch = attr_chain(b)
            if ch:
                bases.append(ch[1][-1] if ch[1] else ch[0])
        ci = ClassInfo(module=mod.modname, name=node.name, bases=bases,
                       node=node, path=mod.rel)
        for stmt in node.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ci.methods[stmt.name] = stmt  # type: ignore[assignment]
            if any(isinstance(d, ast.Name) and d.id == "property"
                   for d in stmt.decorator_list):
                chain = _property_alias(stmt)
                if chain:
                    ci.property_aliases[stmt.name] = chain
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    cn = call_name(sub)
                    if cn == "Thread":
                        ci.spawns_threads = True
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    self._scan_attr_assign(ci, sub, mod)
        self.classes[ci.key] = ci
        self.class_by_name.setdefault(ci.name, []).append(ci)

    def _scan_attr_assign(self, ci: ClassInfo, stmt, mod: ModuleInfo) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        value = stmt.value
        if value is None or not isinstance(value, ast.Call):
            return
        for t in targets:
            if not (isinstance(t, ast.Attribute) and
                    isinstance(t.value, ast.Name) and t.value.id == "self"):
                continue
            li = self._lock_from_call(value, f"{ci.module}.{ci.name}",
                                      t.attr, mod.rel, owner_cls=ci)
            if li is not None:
                ci.lock_attrs.setdefault(t.attr, li)
                continue
            cn = call_name(value)
            if cn and cn in self.class_by_name or cn and cn[:1].isupper():
                ci.attr_types.setdefault(t.attr, cn)

    def _lock_from_call(self, value: ast.Call, owner: str, attr: str,
                        path: str, owner_cls: ClassInfo | None = None
                        ) -> LockInfo | None:
        cn = call_name(value)
        if cn in LOCK_CTORS:
            return LockInfo(attr=attr, owner=owner, name=f"{owner}.{attr}",
                            kind="rlock" if cn == "RLock" else "lock",
                            line=value.lineno, path=path)
        if cn in NAMED_LOCK_CTORS:
            arg0 = value.args[0] if value.args else None
            name = (arg0.value if isinstance(arg0, ast.Constant)
                    and isinstance(arg0.value, str) else f"{owner}.{attr}")
            return LockInfo(attr=attr, owner=owner, name=name, kind="named",
                            line=value.lineno, path=path)
        if cn in CONDITION_CTORS:
            alias = None
            if value.args:
                ch = attr_chain(value.args[0])
                if ch and ch[0] == "self" and ch[1]:
                    alias = tuple(ch[1])
            return LockInfo(attr=attr, owner=owner, name=f"{owner}.{attr}",
                            kind="condition", alias_chain=alias,
                            line=value.lineno, path=path)
        return None

    # -- lock resolution ----------------------------------------------------
    def resolve_lock(self, expr: ast.expr, cls: ClassInfo | None,
                     modname: str) -> LockInfo | None:
        """Resolve a with-item / lock expression to a LockInfo, or None.

        Handles: self.X, self.X.Y (via attr types), bare module globals,
        @property aliases, Condition aliases, and — for non-self receivers
        like ``conn._session.lock`` — package-wide unique attribute-name
        matching, two trailing components deep."""
        ch = attr_chain(expr)
        if ch is None:
            return None
        base, attrs = ch
        if base == "self" and cls is not None:
            return self._resolve_self_chain(attrs, cls)
        if not attrs:
            li = self.module_locks.get((modname, base))
            return li
        return self._resolve_unique_chain(attrs)

    def _deref(self, li: LockInfo | None, cls: ClassInfo | None) -> LockInfo | None:
        """Follow a Condition(self.X) alias to the real lock."""
        seen = 0
        while li is not None and li.alias_chain and cls is not None and seen < 4:
            nxt = self._resolve_self_chain(list(li.alias_chain), cls)
            if nxt is None or nxt is li:
                return li
            li = nxt
            seen += 1
        return li

    def _resolve_self_chain(self, attrs: list[str],
                            cls: ClassInfo) -> LockInfo | None:
        if not attrs:
            return None
        locks = self.family_locks(cls)
        props = self.family_properties(cls)
        types = self.family_attr_types(cls)
        a0 = attrs[0]
        if len(attrs) == 1:
            if a0 in locks:
                return self._deref(locks[a0], cls)
            if a0 in props:
                return self._resolve_self_chain(list(props[a0]), cls)
            return None
        if a0 in types:
            target = self.class_by_name.get(types[a0], [])
            if len(target) == 1:
                tcls = target[0]
                tl = self.family_locks(tcls)
                if attrs[1] in tl and len(attrs) == 2:
                    return self._deref(tl[attrs[1]], tcls)
                tp = self.family_properties(tcls)
                if attrs[1] in tp and len(attrs) == 2:
                    return self._resolve_self_chain(list(tp[attrs[1]]), tcls)
        return self._resolve_unique_chain(attrs)

    def _resolve_unique_chain(self, attrs: list[str]) -> LockInfo | None:
        last = attrs[-1]
        cands = self.lock_attr_index.get(last, [])
        if len(cands) == 1:
            return cands[0]
        if len(cands) > 1 and len(attrs) >= 2:
            pen = attrs[-2]
            owners = self.attr_type_index.get(pen, set())
            narrowed = [c for c in cands
                        if c.owner.rsplit(".", 1)[-1] in owners]
            if len(narrowed) == 1:
                return narrowed[0]
        return None


def _property_alias(fn: ast.FunctionDef) -> tuple[str, ...] | None:
    """``@property def _lock(self): return self._session.lock`` ->
    ("_session", "lock")."""
    body = [s for s in fn.body
            if not (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))]
    if len(body) != 1 or not isinstance(body[0], ast.Return):
        return None
    ch = attr_chain(body[0].value) if body[0].value is not None else None
    if ch and ch[0] == "self" and ch[1]:
        return tuple(ch[1])
    return None
