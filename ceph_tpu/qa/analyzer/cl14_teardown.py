"""CL14 — start/stop teardown symmetry (cephlife).

CL13 proves each function releases what it takes; CL14 proves the
daemon-level contract across the start/stop pair: everything
``start()`` brings up, ``stop()``/``shutdown()`` must bring down, in
an order that doesn't strand a dependency, surviving a raise
mid-teardown, and without re-topologizing process-wide singletons on
a second daemon.  This is the static twin of
``qa.smoke_util.assert_no_leaked_threads`` (and the bug class behind
the PR-7 cephadm zombie-teardown).

A class is in scope when its family (mixin closure) defines both a
``start()`` and a ``stop()``/``shutdown()``.  Acquire records in
start, in source order:

- sub-lifecycle starts: ``self.X.start()``, ``for m in self.X:
  m.start()``
- threads: ``self.X = threading.Thread(...)`` + ``self.X.start()``,
  or a started local appended to ``self.X``
- ``SENTINEL.acquire(...)`` refcounts, ``*.add_observer(...)``,
  ``*.register_command(...)``
- singleton installers: calls to module-level functions that assign a
  module global

Findings:

- ``stop-missing:<Class>:<res>`` — acquired in start, never released
  (stop/shutdown/join/deregister) anywhere in the stop body or the
  same-class helpers it calls.
- ``stop-order:<Class>:<a>,<b>`` — two resources released in the
  SAME order they started: teardown must reverse bring-up (the pool
  drained before its flusher stops, the tick thread joined after the
  messenger it sends through is gone).
- ``stop-fragile:<Class>:<step>`` — a teardown call that may raise,
  not wrapped in try/except (or handed to a best-effort runner as a
  bound method), with further teardown steps after it: one bad
  subsystem strands the rest.
- ``restart-unsafe:<Class>:<fn>`` — start() calls a module-global
  installer with no first-daemon-wins guard (no early-return /
  conditional install), so a second daemon in the process silently
  re-topologizes shared state.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Config, Finding, ModuleInfo
from .symbols import ClassInfo, SymbolTable, attr_chain, call_name

_STOP_NAMES = ("stop", "shutdown")
_RELEASE_METHODS = frozenset({"stop", "shutdown", "join", "close",
                              "umount", "release", "disarm",
                              "remove_observer", "unregister_command"})
#: teardown steps that realistically cannot raise (pure signal/join)
_SAFE_TEARDOWN = frozenset({"join", "set", "clear", "is_set"})


@dataclass
class _Acq:
    kind: str      # "sub" | "thread" | "sentinel" | "observer" |
    #                "command" | "singleton"
    res: str       # attr name / global name
    line: int
    order: int


def _self_attr(node: ast.expr) -> str | None:
    """'X' for a ``self.X`` expression (one level)."""
    ch = attr_chain(node)
    if ch and ch[0] == "self" and len(ch[1]) == 1:
        return ch[1][0]
    return None


def _loop_binds(body: ast.AST) -> dict[str, str]:
    """loop-var -> self attr for ``for v in self.X[...]`` (and
    ``.values()``/``reversed()`` wrappers)."""
    binds: dict[str, str] = {}
    for node in ast.walk(body):
        if not isinstance(node, (ast.For, ast.comprehension)):
            continue
        it = node.iter
        while isinstance(it, ast.Call) and call_name(it) in (
                "reversed", "list", "sorted") and it.args:
            it = it.args[0]
        if isinstance(it, ast.Call) and isinstance(it.func,
                                                   ast.Attribute) \
                and it.func.attr in ("values", "items", "keys"):
            it = it.func.value
        attr = _self_attr(it)
        tgt = node.target
        if attr is not None and isinstance(tgt, ast.Name):
            binds[tgt.id] = attr
        elif attr is not None and isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                if isinstance(el, ast.Name):
                    binds[el.id] = attr
    return binds


class _ClassCheck:
    def __init__(self, ci: ClassInfo, sym: SymbolTable, mod: ModuleInfo,
                 installers: dict[str, bool], report) -> None:
        self.ci = ci
        self.sym = sym
        self.mod = mod
        self.installers = installers  # fn name -> has first-wins guard
        self.report = report

    # -- start(): ordered acquires -----------------------------------------
    def acquires(self, start_fn: ast.AST) -> list[_Acq]:
        out: list[_Acq] = []
        binds = _loop_binds(start_fn)
        started_locals: set[str] = set()
        thread_attrs: set[str] = set()
        for node in ast.walk(start_fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) \
                    and call_name(node.value) == "Thread":
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        thread_attrs.add(a)
                    elif isinstance(t, ast.Name):
                        started_locals.add(t.id)
        for node in ast.walk(start_fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in self.installers:
                    out.append(_Acq("singleton", f.id, node.lineno,
                                    len(out)))
                continue
            if not isinstance(f, ast.Attribute):
                continue
            recv, meth = f.value, f.attr
            if meth == "start":
                a = _self_attr(recv)
                if a is not None:
                    kind = "thread" if a in thread_attrs else "sub"
                    out.append(_Acq(kind, a, node.lineno, len(out)))
                elif isinstance(recv, ast.Name) and recv.id in binds:
                    out.append(_Acq("sub", binds[recv.id], node.lineno,
                                    len(out)))
            elif meth == "append":
                # self.X.append(t) for a started local thread
                a = _self_attr(recv)
                if a is not None and node.args and isinstance(
                        node.args[0], ast.Name) \
                        and node.args[0].id in started_locals:
                    out.append(_Acq("thread", a, node.lineno,
                                    len(out)))
            elif meth == "acquire" and isinstance(recv, ast.Name) \
                    and recv.id == "SENTINEL":
                out.append(_Acq("sentinel", "SENTINEL", node.lineno,
                                len(out)))
            elif meth == "add_observer":
                out.append(_Acq("observer", "observer", node.lineno,
                                len(out)))
            elif meth == "register_command":
                out.append(_Acq("command", "admin-command", node.lineno,
                                len(out)))
        # one record per resource (loops start many members of one attr)
        seen: set[tuple[str, str]] = set()
        uniq = []
        for a in out:
            if (a.kind, a.res) not in seen:
                seen.add((a.kind, a.res))
                uniq.append(a)
        return uniq

    # -- stop(): the release inventory, in order ---------------------------
    def _stop_nodes(self, stop_fn: ast.AST):
        """Walk the stop body plus one level of same-class helper
        methods it calls (``self._teardown()`` style)."""
        yield from ast.walk(stop_fn)
        methods = {m: fn for c in self.sym.family_members(self.ci)
                   for m, fn in c.methods.items()}
        for node in ast.walk(stop_fn):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                # self._helper() — Attribute value is bare `self`
                ch = attr_chain(node.func)
                if ch and ch[0] == "self" and len(ch[1]) == 1 \
                        and ch[1][0] in methods \
                        and ch[1][0] not in _STOP_NAMES:
                    yield from ast.walk(methods[ch[1][0]])

    def releases(self, stop_fn: ast.AST) -> list[tuple[str, int]]:
        """(resource, line) for every teardown touch in stop, in
        source order.  Bound-method references passed to a best-effort
        runner (``_stop_quietly("osd", osd.shutdown)``) count — the
        matcher reads Attribute nodes, not just calls."""
        binds = _loop_binds(stop_fn)
        all_nodes = list(self._stop_nodes(stop_fn))
        for n in all_nodes:
            # plain alias: ``t = self._thread`` then ``t.join()``
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                a = _self_attr(n.value)
                if a is not None:
                    binds.setdefault(n.targets[0].id, a)
        rel: list[tuple[str, int]] = []
        seen: set[str] = set()
        nodes = sorted(
            (n for n in all_nodes
             if isinstance(n, ast.Attribute)
             and n.attr in _RELEASE_METHODS),
            key=lambda n: (n.lineno, n.col_offset))
        for node in nodes:
            res: str | None = None
            if node.attr == "remove_observer":
                res = "observer"
            elif node.attr == "unregister_command":
                res = "admin-command"
            elif node.attr == "release" and isinstance(
                    node.value, ast.Name) and node.value.id == "SENTINEL":
                res = "SENTINEL"
            else:
                a = _self_attr(node.value)
                if a is not None:
                    res = a
                elif isinstance(node.value, ast.Name) \
                        and node.value.id in binds:
                    res = binds[node.value.id]
            if res is not None and res not in seen:
                seen.add(res)
                rel.append((res, node.lineno))
        return rel

    # -- the findings ------------------------------------------------------
    def run(self, start_fn, stop_fn, stop_name: str) -> None:
        acqs = self.acquires(start_fn)
        rels = self.releases(stop_fn)
        rel_by_res = {r: i for i, (r, _ln) in enumerate(rels)}
        cname = self.ci.name

        # stop-missing
        for a in acqs:
            if a.kind == "singleton":
                self._restart_unsafe(a, cname)
                continue
            if a.res not in rel_by_res:
                self.report(
                    "stop-missing", a.line, f"{cname}:{a.res}",
                    f"{cname}.start() brings up {a.kind} '{a.res}' "
                    f"(line {a.line}) but {cname}.{stop_name}() never "
                    f"stops/joins/deregisters it — a zombie across "
                    f"restart")

        # stop-order: consecutive releases of start-ordered resources
        # must reverse the bring-up order
        ordered = [(a, rel_by_res[a.res]) for a in acqs
                   if a.kind != "singleton" and a.res in rel_by_res]
        ordered.sort(key=lambda p: p[1])  # by release position
        for (a1, _r1), (a2, _r2) in zip(ordered, ordered[1:]):
            if a1.order < a2.order:
                line = rels[rel_by_res[a2.res]][1]
                self.report(
                    "stop-order", line,
                    f"{cname}:{a1.res},{a2.res}",
                    f"{cname}.{stop_name}() releases '{a1.res}' before "
                    f"'{a2.res}' though start() brought '{a1.res}' up "
                    f"first — teardown must reverse bring-up, or "
                    f"'{a2.res}' runs against a dependency that is "
                    f"already gone")

        self._fragile(stop_fn, stop_name, cname)

    def _fragile(self, stop_fn, stop_name: str, cname: str) -> None:
        """The first unprotected may-raise teardown CALL with further
        teardown after it.  Calls under a try and bound methods handed
        to a runner are protected by construction."""
        calls = [n for n in ast.walk(stop_fn)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr in _RELEASE_METHODS
                 and n.func.attr not in _SAFE_TEARDOWN]
        if len(calls) < 2:
            return
        protected: set[int] = set()
        for node in ast.walk(stop_fn):
            if isinstance(node, ast.Try):
                for sub in ast.walk(node):
                    protected.add(id(sub))
        calls.sort(key=lambda n: (n.lineno, n.col_offset))
        for c in calls[:-1]:  # the last step strands nothing
            if id(c) not in protected:
                what = ast.unparse(c.func)
                self.report(
                    "stop-fragile", c.lineno,
                    f"{cname}:{what}",
                    f"'{what}()' in {cname}.{stop_name}() can raise "
                    f"and is not wrapped — a failure here strands "
                    f"every teardown step after it (wrap each step "
                    f"best-effort, mgr/daemon.py style)")
                return

    def _restart_unsafe(self, a: _Acq, cname: str) -> None:
        if not self.installers.get(a.res, True):
            self.report(
                "restart-unsafe", a.line, f"{cname}:{a.res}",
                f"{cname}.start() calls '{a.res}()' which installs a "
                f"module global with no first-daemon-wins guard — a "
                f"second daemon in the process re-topologizes shared "
                f"state (guard with an applied-flag early return, "
                f"device_policy.configure_device_policy style)")


def _installer_index(mods: list[ModuleInfo]) -> dict[str, bool]:
    """Module-level functions that assign a module global:
    name -> has a first-wins guard (any If around / before the
    install, e.g. ``if _applied: return`` or a conditional assign)."""
    out: dict[str, bool] = {}
    for mod in mods:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.FunctionDef):
                continue
            globals_ = {n for s in ast.walk(stmt)
                        if isinstance(s, ast.Global) for n in s.names}
            if not globals_:
                continue
            assigns = any(
                isinstance(n, ast.Name) and n.id in globals_
                and isinstance(n.ctx, ast.Store)
                for n in ast.walk(stmt))
            if not assigns:
                continue
            guarded = any(isinstance(n, ast.If) for n in ast.walk(stmt))
            # keep the STRICTEST verdict if the name repeats
            out[stmt.name] = out.get(stmt.name, True) and guarded
    return out


def check(mods: list[ModuleInfo], sym: SymbolTable,
          cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[str, str, str]] = set()
    installers = _installer_index(mods)
    by_rel = {m.rel: m for m in mods}
    for key in sorted(sym.classes):
        ci = sym.classes[key]
        if ci.path.startswith("qa/analyzer/"):
            continue
        methods = sym.family_methods(ci)
        if "start" not in methods:
            continue
        start_owner, start_fn = methods["start"]
        if start_owner.key != ci.key:
            continue  # report once, on the class that defines start()
        stop_pair = next((methods[n] for n in _STOP_NAMES
                          if n in methods), None)
        if stop_pair is None:
            continue
        stop_owner, stop_fn = stop_pair
        stop_name = stop_fn.name
        mod = by_rel.get(ci.path)
        if mod is None:
            continue

        def report(kind, line, ident_tail, msg, _mod=mod):
            ident = f"{kind}:{ident_tail}"
            k = ("CL14", _mod.rel, ident)
            if k not in seen:
                seen.add(k)
                findings.append(
                    Finding("CL14", _mod.rel, line, ident, msg))

        _ClassCheck(ci, sym, mod, installers, report).run(
            start_fn, stop_fn, stop_name)
    return findings
