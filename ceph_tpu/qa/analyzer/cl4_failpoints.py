"""CL4 — failpoint drift.

Three sources of truth must agree on the set of failpoint names:

1. **sites** — ``failpoint("name", ...)`` / ``self._fp_hit("name")`` /
   ``registry().hit|configured("name")`` markers in daemon code;
2. **the registry catalogue** — ``KNOWN_FAILPOINTS`` in
   common/failpoint.py (what `failpoint list`/the thrasher may arm);
3. **the operator docs** — the name table in docs/fault_injection.md.

Drift shapes reported (idents are the failpoint name, so baseline
entries survive renumbering):

- ``site:<name>``  a site literal missing from KNOWN_FAILPOINTS —
  unreachable through validation, invisible to `failpoint list`;
- ``doc:<name>``   a site literal missing from the docs table — the
  operator can't discover it;
- ``orphan-known:<name>``  catalogued but no site marks it — arming it
  silently does nothing (the drift that rots fault-injection suites);
- ``orphan-doc:<name>``    documented but no site — docs promise an
  injection point that does not exist;
- ``arm:<name>``   a ``registry().set/add("name", ...)`` literal naming
  an uncatalogued failpoint (a typo'd arm never fires).

Both the catalogue and the docs table are read statically (AST / table
parse) so the analyzer works on fixture trees without importing them.
"""
from __future__ import annotations

import ast
import re

from .core import Config, Finding, ModuleInfo, parse_source, read_doc, rel_of
from .symbols import SymbolTable

# | `msgr.frame.send` | ... — the docs catalogue is the first backticked
# cell of each table row
_DOC_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.\-]+)`\s*\|")


def parse_known_failpoints(path) -> tuple[set[str], int]:
    """KNOWN_FAILPOINTS literal (set/frozenset/tuple/list/dict of string
    constants) from common/failpoint.py, plus its line for findings."""
    tree, _lines = parse_source(path)
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_FAILPOINTS"
                   for t in targets):
            continue
        if isinstance(value, ast.Call):  # frozenset({...})
            value = value.args[0] if value.args else value
        elts: list[ast.expr] = []
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            elts = value.elts
        elif isinstance(value, ast.Dict):
            elts = [k for k in value.keys if k is not None]
        names = {e.value for e in elts
                 if isinstance(e, ast.Constant) and isinstance(e.value, str)}
        return names, node.lineno
    return set(), 0


def parse_doc_names(path) -> set[str]:
    names: set[str] = set()
    for line in read_doc(path).splitlines():
        m = _DOC_ROW_RE.match(line.strip())
        if m and "." in m.group(1):  # name cells, not header/option cells
            names.add(m.group(1))
    return names


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    if cfg.failpoint_file is None:
        return []
    known, known_line = parse_known_failpoints(cfg.failpoint_file)
    docs = (parse_doc_names(cfg.docs_fault_injection)
            if cfg.docs_fault_injection else None)
    fp_rel = rel_of(cfg, cfg.failpoint_file)
    doc_rel = (rel_of(cfg, cfg.docs_fault_injection)
               if cfg.docs_fault_injection else "")

    findings: list[Finding] = []
    site_names: dict[str, tuple[str, int]] = {}
    arm_names: dict[str, tuple[str, int]] = {}
    for s in sym.failpoint_sites:
        d = site_names if s.kind == "site" else arm_names
        d.setdefault(s.name, (s.path, s.line))

    for name, (path, line) in sorted(site_names.items()):
        if name not in known:
            findings.append(Finding(
                "CL4", path, line, f"site:{name}",
                f"failpoint site {name!r} is not catalogued in "
                f"KNOWN_FAILPOINTS (common/failpoint.py)"))
        if docs is not None and name not in docs:
            findings.append(Finding(
                "CL4", path, line, f"doc:{name}",
                f"failpoint site {name!r} is missing from the "
                f"docs/fault_injection.md name table"))

    for name in sorted(known):
        if name not in site_names:
            findings.append(Finding(
                "CL4", fp_rel, known_line, f"orphan-known:{name}",
                f"KNOWN_FAILPOINTS entry {name!r} has no failpoint site "
                f"— arming it does nothing"))
        if docs is not None and name not in docs:
            findings.append(Finding(
                "CL4", fp_rel, known_line, f"undoc-known:{name}",
                f"KNOWN_FAILPOINTS entry {name!r} is missing from the "
                f"docs/fault_injection.md name table"))

    if docs is not None:
        for name in sorted(docs):
            if name not in site_names and name not in known:
                findings.append(Finding(
                    "CL4", doc_rel, 1, f"orphan-doc:{name}",
                    f"documented failpoint {name!r} has neither a site "
                    f"nor a KNOWN_FAILPOINTS entry"))

    for name, (path, line) in sorted(arm_names.items()):
        if name not in known:
            findings.append(Finding(
                "CL4", path, line, f"arm:{name}",
                f"arming uncatalogued failpoint {name!r} — a typo here "
                f"never fires"))
    return findings
