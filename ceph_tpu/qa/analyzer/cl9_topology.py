"""CL9 — device-topology discipline (cephtopo).

The multi-chip data plane (ROADMAP) dies on ambient topology: every
scattered ``jax.devices()`` / ``Mesh(...)`` / ``jax.default_backend()``
probe hard-codes "whatever this process happens to see" and keeps the
same OSD code from serving a laptop test, an 8-chip mesh, and a
sentinel-shrunk degraded mesh.  Exactly ONE module — the policy
allowlist ``cfg.cl9_policy_modules``, default
``common/device_policy.py`` — may touch the runtime's topology;
everything else receives a constructor-injected ``DevicePolicy``.

Finding kinds (ident ``<scope>:<kind>``, scope = enclosing function or
``<module>``):

- ``ambient-devices`` — ``jax.devices()`` / ``jax.local_devices()``
  outside the policy module.  Use ``DevicePolicy.devices()`` /
  ``.default_device()``.
- ``device-index`` — integer-literal subscript of a devices() result
  (directly or via a name bound from one): positional chip addressing,
  the ``jax.device_put(x, jax.devices()[i])`` anti-pattern.  A
  sentinel-shrunk mesh renumbers; ask the policy for a device.
- ``ambient-mesh`` — ``Mesh(...)`` constructed outside the policy.
  Use ``DevicePolicy.mesh()`` or ``device_policy.mesh_over()``.
- ``ambient-backend`` — ``jax.default_backend()`` probes outside the
  policy: dispatch decisions (pallas, donation, CRUSH engine) must
  respect the cpu-fallback variant, so ask ``policy.backend()``.
- ``public-jit`` (``cfg.cl9_jit_dirs``, default ops/) — a PUBLIC
  module-level jitted entry point (``name = jax.jit(...)`` or a public
  ``@jax.jit`` def).  Jit entry points in ops/ stay private and
  dispatch through a telemetry/policy-recording wrapper (the
  ``apply_matrix_jax`` / ``crush_do_rule_batch`` pattern); a public
  jitted name invites callers to bypass that seam.
- ``donate`` — a ``donate_argnums`` annotation in a module that never
  references the device-pool seam (``ops/device_pool.py``): donation
  without the pool means no caller can route recycled buffers into the
  donated slot, so the annotation either does nothing or silently
  aliases a buffer the caller still holds.

Deliberate ambient sites carry a reasoned ``# noqa: CL9`` (the
sentinel's per-device probe must see the raw topology — it FEEDS the
policy's shrink) or a justified baseline entry; the tier-1
whole-package gate (tests/test_analyzer_topo.py) pins the count of
unsuppressed findings at zero.
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, attr_chain, call_name

_DEVICE_CALLS = {"devices", "local_devices"}
_BACKEND_CALLS = {"default_backend"}
_JAX_ROOTS = {"jax"}
#: names whose presence marks a module as pool-seam-aware (the donate
#: kind's exemption): importing/defining any of these means buffers can
#: route through ops/device_pool.py
_POOL_MARKS = {"device_pool", "DevicePool", "POOL", "donation_supported"}


def _is_jax_probe(node: ast.Call, names: set[str]) -> bool:
    """jax.devices() / jax.local_devices() / jax.default_backend()."""
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in names:
        return False
    chain = attr_chain(f)  # None when the chain roots in an expression
    return chain is not None and chain[0] in _JAX_ROOTS


def _is_mesh_ctor(node: ast.Call) -> bool:
    """Mesh(...) / jax.sharding.Mesh(...) — constructing a topology."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "Mesh"
    if isinstance(f, ast.Attribute):
        return f.attr == "Mesh"
    return False


def _is_jit_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func):
                return True
            if call_name(dec) == "partial" and dec.args \
                    and _is_jit_expr(dec.args[0]):
                return True
    return False


def _references_pool(mod: ModuleInfo) -> bool:
    for node in mod.walk():
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "device_pool":
            return True
        if isinstance(node, ast.Name) and node.id in _POOL_MARKS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _POOL_MARKS:
            return True
        if isinstance(node, (ast.FunctionDef, ast.ClassDef)) \
                and node.name in _POOL_MARKS:
            return True
    return False


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    jit_dirs = set(cfg.cl9_jit_dirs)
    for mod in mods:
        if mod.rel in cfg.cl9_policy_modules:
            continue  # the ONE place ambient topology is legal
        v = _TopoVisitor(mod, pool_aware=_references_pool(mod))
        v.run()
        findings.extend(v.findings)
        if mod.topdir() in jit_dirs:
            findings.extend(_public_jit(mod))
    return findings


def _public_jit(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    seen: set[str] = set()

    def report(name: str, line: int) -> None:
        ident = f"public-jit:{name}"
        if ident in seen:
            return
        seen.add(ident)
        out.append(Finding(
            "CL9", mod.rel, line, ident,
            f"public jitted entry point `{name}` — keep jit handles "
            f"private and dispatch through a telemetry/policy wrapper "
            f"(the apply_matrix_jax pattern), or # noqa with the "
            f"wrapper that owns it"))

    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            jit_like = _is_jit_expr(call.func) or (
                call_name(call) == "partial" and call.args
                and _is_jit_expr(call.args[0]))
            if not jit_like:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name) and not t.id.startswith("_"):
                    report(t.id, stmt.lineno)
        elif isinstance(stmt, ast.FunctionDef):
            if not stmt.name.startswith("_") and _jit_decorated(stmt):
                report(stmt.name, stmt.lineno)
    return out


class _TopoVisitor:
    """One pass over a module: ambient probes, mesh construction,
    device-index addressing, and pool-less donation, each attributed to
    the enclosing function scope (``<module>`` at top level)."""

    def __init__(self, mod: ModuleInfo, pool_aware: bool):
        self.mod = mod
        self.pool_aware = pool_aware
        self.findings: list[Finding] = []
        self._seen: set[str] = set()

    def run(self) -> None:
        self._walk_scope(self.mod.tree.body, "<module>")

    def _walk_scope(self, body: list[ast.stmt], scope: str) -> None:
        """Visit this scope's own nodes in source order; nested defs
        (including methods) recurse as their own scope so a finding is
        attributed — and deduped — exactly once."""
        devices_names: set[str] = set()  # names bound from devices()
        queue: list[ast.AST] = list(body)
        i = 0
        nested: list[ast.FunctionDef] = []
        while i < len(queue):
            node = queue[i]
            i += 1
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            if isinstance(node, ast.ClassDef):
                queue.extend(node.body)  # methods recurse via nested
                continue
            if isinstance(node, ast.Assign) \
                    and self._mentions_devices_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        devices_names.add(t.id)
            self._check_node(node, scope, devices_names)
            queue.extend(ast.iter_child_nodes(node))
        for fn in nested:
            self._walk_scope(fn.body, fn.name)

    @staticmethod
    def _mentions_devices_call(expr: ast.expr) -> bool:
        return any(isinstance(n, ast.Call)
                   and _is_jax_probe(n, _DEVICE_CALLS)
                   for n in ast.walk(expr))

    def _check_node(self, node: ast.AST, scope: str,
                    devices_names: set[str]) -> None:
        if isinstance(node, ast.Call):
            if _is_jax_probe(node, _DEVICE_CALLS):
                self._report(node, scope, "ambient-devices",
                             f"ambient jax.{node.func.attr}() — topology "
                             f"belongs to the injected DevicePolicy "
                             f"(common/device_policy.py)")
            elif _is_jax_probe(node, _BACKEND_CALLS):
                self._report(node, scope, "ambient-backend",
                             "ambient jax.default_backend() — dispatch "
                             "must ask policy.backend() so the "
                             "cpu-fallback variant is honored")
            elif _is_mesh_ctor(node):
                self._report(node, scope, "ambient-mesh",
                             "Mesh(...) constructed outside the policy "
                             "module — use DevicePolicy.mesh() / "
                             "device_policy.mesh_over()")
            for kw in node.keywords:
                if kw.arg == "donate_argnums" and not self.pool_aware:
                    self._report(
                        node, scope, "donate",
                        "donate_argnums in a module that never touches "
                        "the device-pool seam (ops/device_pool.py) — "
                        "callers cannot route recycled buffers into the "
                        "donated slot")
        elif isinstance(node, ast.Subscript):
            idx = node.slice
            if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
                base = node.value
                is_dev = (isinstance(base, ast.Call)
                          and _is_jax_probe(base, _DEVICE_CALLS)) or (
                    isinstance(base, ast.Name)
                    and base.id in devices_names)
                if is_dev:
                    self._report(node, scope, "device-index",
                                 "integer device index into an ambient "
                                 "device list — a sentinel-shrunk mesh "
                                 "renumbers; ask the policy for a device")

    def _report(self, node: ast.AST, scope: str, kind: str,
                msg: str) -> None:
        ident = f"{scope}:{kind}"
        n = 2
        while ident in self._seen:
            ident = f"{scope}:{kind}:{n}"
            n += 1
        self._seen.add(ident)
        self.findings.append(Finding(
            "CL9", self.mod.rel, getattr(node, "lineno", 1), ident, msg))
