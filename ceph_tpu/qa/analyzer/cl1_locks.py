"""CL1 — lock discipline.

Three sub-checks over the whole package:

1. **Order inversions** (`lock-cycle:<A>-><B>`): the static lock-order
   graph is derived from lexical ``with <lock>:`` nesting plus
   one-level-resolved calls (``self.m()`` and ``self.<typed attr>.m()``)
   made while a lock is held — every lock the callee transitively
   acquires is ordered after every lock held at the call site.  Any
   strongly-connected component in that graph is the ABBA shape
   common/lockdep.py would catch at runtime, reported at analysis time.

2. **Blocking under a lock** (`<fn>:blocking:<call>:<lock>`): a lexical
   call to a known-blocking primitive (time.sleep, socket
   send/recv/accept/dial, messenger send_message, store
   queue_transaction) inside a ``with <lock>:`` body.  Condition
   .wait/.wait_for are deliberately NOT in the set — they release their
   lock.  Sites that hold a lock by design (e.g. the messenger's
   one-session-lock send path) carry a baseline entry with the
   justification.

3. **Raw locks in concurrency-heavy dirs** (`raw-lock:<attr>`): a bare
   threading.Lock()/RLock() in osd/, mon/, msg/, store/, client/ is
   invisible to lockdep's runtime cycle detection; use
   common.lockdep.make_lock("subsys::purpose").
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Config, Finding, ModuleInfo
from .symbols import ClassInfo, SymbolTable, attr_chain

# call-name patterns considered blocking.  time.sleep is matched with its
# receiver (bare ``sleep`` alone could be anything); the rest by attr name.
_BLOCKING_ATTRS = {
    "sendall": "socket send",
    "recv": "socket recv",
    "accept": "socket accept",
    "create_connection": "socket dial",
    "send_message": "messenger send",
    "queue_transaction": "store commit",
}


@dataclass
class _FnInfo:
    qual: str
    cls: ClassInfo | None
    mod: ModuleInfo
    node: ast.FunctionDef
    direct_acquires: set[str] = field(default_factory=set)
    callees: set[str] = field(default_factory=set)
    # (held_locks_tuple, callee_qual, line)
    calls_while_held: list[tuple[tuple[str, ...], str, int]] = field(default_factory=list)
    # (held_locks_tuple, blocking_label, call_repr, line)
    blocking: list[tuple[tuple[str, ...], str, str, int]] = field(default_factory=list)
    edges: list[tuple[str, str, int]] = field(default_factory=list)


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    prime_class_cache(sym)
    fns: dict[str, _FnInfo] = {}
    for mod in mods:
        for cls, fn in _iter_functions(mod):
            qual = (f"{mod.modname}.{cls.name}.{fn.name}" if cls
                    else f"{mod.modname}.{fn.name}")
            info = _FnInfo(qual=qual, cls=cls, mod=mod, node=fn)
            _Walker(info, sym).visit_body(fn.body)
            fns[qual] = info

    # method-name -> quals (for self.m() resolution within a family, and
    # typed-attr resolution across families)
    trans = _transitive_acquires(fns, sym)

    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, path: str, line: int, why: str) -> None:
        if a != b:
            edges.setdefault((a, b), (path, line, why))

    for info in fns.values():
        for a, b, line in info.edges:
            add_edge(a, b, info.mod.rel, line, f"with-nesting in {info.qual}")
        for held, callee, line in info.calls_while_held:
            for acq in trans.get(callee, ()):  # transitive callee acquires
                for h in held:
                    add_edge(h, acq, info.mod.rel, line,
                             f"{info.qual} calls {callee} holding {h}")

    findings: list[Finding] = []
    for scc in _sccs({a for a, _ in edges} | {b for _, b in edges},
                     edges.keys()):
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        for (a, b), (path, line, why) in sorted(edges.items()):
            if a in scc and b in scc:
                findings.append(Finding(
                    "CL1", path, line, f"lock-cycle:{a}->{b}",
                    f"lock-order inversion: {a} -> {b} closes a cycle "
                    f"through {{{', '.join(cyc)}}} ({why})"))

    for info in fns.values():
        for held, label, rep, line in info.blocking:
            findings.append(Finding(
                "CL1", info.mod.rel, line,
                f"{_short(info.qual)}:blocking:{rep}:{held[-1]}",
                f"blocking call {rep} ({label}) while holding "
                f"lock(s) {', '.join(held)}"))

    raw_dirs = set(cfg.cl1_raw_lock_dirs)
    for cls in sym.classes.values():
        top = cls.path.split("/", 1)[0] if "/" in cls.path else ""
        if top not in raw_dirs:
            continue
        for attr, li in cls.lock_attrs.items():
            if li.kind in ("lock", "rlock"):
                findings.append(Finding(
                    "CL1", cls.path, li.line, f"raw-lock:{cls.name}.{attr}",
                    f"raw threading.{'RLock' if li.kind == 'rlock' else 'Lock'}"
                    f" {cls.name}.{attr} is invisible to lockdep; use "
                    f"common.lockdep.make_lock(...)"))
    return findings


def _short(qual: str) -> str:
    return qual.rsplit(".", 2)[-1] if qual.count(".") < 2 else \
        ".".join(qual.rsplit(".", 2)[-2:])


def _iter_functions(mod: ModuleInfo):
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.FunctionDef):
            yield None, stmt
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, ast.FunctionDef):
                    # the symbol table holds the canonical ClassInfo
                    yield _lookup_class(mod, stmt.name), sub


_class_cache: dict = {}


def _lookup_class(mod: ModuleInfo, name: str):
    return _class_cache.get((mod.modname, name))


class _Walker:
    """Lexical walk of one function body tracking the held-lock stack."""

    def __init__(self, info: _FnInfo, sym: SymbolTable):
        self.info = info
        self.sym = sym
        self.held: list[str] = []

    def visit_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            self._with(stmt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs execute later, not under this lock scope
        for node in ast.iter_child_nodes(stmt):
            self.visit_node(node)

    def visit_node(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.stmt):
            self.visit_stmt(node)
            return
        for child in ast.iter_child_nodes(node):
            self.visit_node(child)

    def _with(self, stmt: ast.With) -> None:
        pushed = 0
        for item in stmt.items:
            li = self.sym.resolve_lock(item.context_expr, self.info.cls,
                                       self.info.mod.modname)
            if li is None:
                continue
            self.info.direct_acquires.add(li.name)
            for h in self.held:
                if h != li.name:
                    self.info.edges.append((h, li.name, stmt.lineno))
            self.held.append(li.name)
            pushed += 1
        for item in stmt.items:
            # still scan the with-expressions themselves for calls
            self.visit_node(item.context_expr)
        self.visit_body(stmt.body)
        for _ in range(pushed):
            self.held.pop()

    def _call(self, node: ast.Call) -> None:
        for child in ast.iter_child_nodes(node):
            self.visit_node(child)
        if not self.held:
            self._record_callee(node, record_edges=False)
            return
        held = tuple(self.held)
        # blocking primitives
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                    and f.value.id == "time":
                self.info.blocking.append((held, "sleep", "time.sleep",
                                           node.lineno))
            elif f.attr in _BLOCKING_ATTRS:
                self.info.blocking.append(
                    (held, _BLOCKING_ATTRS[f.attr], f.attr, node.lineno))
        self._record_callee(node, record_edges=True, held=held)

    def _record_callee(self, node: ast.Call, record_edges: bool,
                       held: tuple[str, ...] = ()) -> None:
        quals = self._callee_quals(node)
        for q in quals:
            self.info.callees.add(q)
            if record_edges:
                self.info.calls_while_held.append((held, q, node.lineno))

    def _callee_quals(self, node: ast.Call) -> list[str]:
        f = node.func
        cls = self.info.cls
        sym = self.sym
        # self.m(...)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and cls is not None:
                m = sym.family_methods(cls).get(f.attr)
                if m:
                    owner, _fn = m
                    return [f"{owner.module}.{owner.name}.{f.attr}"]
                return []
            # bare module function imported or local: NAME(...)
        if isinstance(f, ast.Name):
            return [f"{self.info.mod.modname}.{f.id}"]
        # self.ATTR.m(...) via the instance-attribute type map
        ch = attr_chain(f)
        if ch and ch[0] == "self" and len(ch[1]) == 2 and cls is not None:
            a, m = ch[1]
            t = sym.family_attr_types(cls).get(a)
            if t:
                targets = sym.class_by_name.get(t, [])
                if len(targets) == 1 and m in targets[0].methods:
                    tc = targets[0]
                    return [f"{tc.module}.{tc.name}.{m}"]
        return []


def _transitive_acquires(fns: dict[str, _FnInfo],
                         sym: SymbolTable) -> dict[str, set[str]]:
    acq = {q: set(i.direct_acquires) for q, i in fns.items()}
    changed = True
    while changed:
        changed = False
        for q, info in fns.items():
            for callee in info.callees:
                extra = acq.get(callee)
                if extra and not extra <= acq[q]:
                    acq[q] |= extra
                    changed = True
    return acq


def _sccs(nodes: set[str], edge_keys) -> list[set[str]]:
    """Tarjan's strongly-connected components, iterative."""
    out: dict[str, list[str]] = {n: [] for n in nodes}
    for a, b in edge_keys:
        out[a].append(b)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(out[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(out[w])))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


def prime_class_cache(sym: SymbolTable) -> None:
    _class_cache.clear()
    for ci in sym.classes.values():
        _class_cache[(ci.module, ci.name)] = ci
