"""CL7 — error-path lint.

Error paths are where distributed-storage bugs hide: the happy path is
exercised by every test, the except arm only by the failure the thrasher
(qa/thrasher.py) happens to draw.  Three shapes, each a known rot
pattern from the failpoint/thrash work:

- ``swallow:*``      a bare ``except:`` / ``except Exception:`` whose
  body neither re-raises, logs, nor recovers (pure ``pass``/``continue``)
  — the error vanishes and the daemon limps on in an undefined state.
  Handlers that DO something (set a fallback, clean up, narrow retry)
  stay quiet; a deliberate best-effort swallow carries ``# noqa: CL7``
  with its justification or a baseline entry.
- ``no-timeout:*``   a blocking wait with no timeout: ``Condition.wait
  /wait_for`` without a timeout argument (a lost notify parks the thread
  forever — the reference bounds every sub-op wait, see
  osd_subop_reply_timeout), ``queue.get()`` with neither timeout nor
  block=False, and ``sock.recv`` in a class that never arms
  ``settimeout`` anywhere (an unbounded read off a dead peer).
- ``reset-race:*``   ``ms_handle_reset`` / ``ms_handle_remote_reset``
  mutating instance state outside any ``with <lock>:`` block in a class
  that owns locks.  Reset callbacks run on messenger rx threads
  concurrently with the dispatch path — every mutation there needs the
  owning lock (the monitor's _subs_lock pattern).
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo
from .symbols import ClassInfo, SymbolTable, attr_chain, call_name

_BROAD = {"Exception", "BaseException"}
_LOGGISH = {"dout", "debug", "info", "warning", "warn", "error",
            "exception", "critical", "log", "print"}
_QUEUE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_MUTATORS = {"pop", "append", "add", "remove", "clear", "update",
             "discard", "popitem", "extend", "insert", "setdefault"}
_RESET_METHODS = {"ms_handle_reset", "ms_handle_remote_reset"}


def _exc_names(t: ast.expr | None) -> list[str] | None:
    """Exception-type names of a handler; None for a bare except."""
    if t is None:
        return None
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _has_raise_or_log(body: list[ast.stmt]) -> bool:
    for node in ast.walk(ast.Module(body=body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and call_name(node) in _LOGGISH:
            return True
    return False


def _pure_swallow(body: list[ast.stmt]) -> bool:
    """True when the handler only passes/continues — nothing recovered,
    nothing recorded."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / Ellipsis
        return False
    return True


class _FnCtx:
    """Per-function name environment for the queue/condition resolution."""

    def __init__(self, fn: ast.FunctionDef):
        self.queueish: set[str] = set()
        for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs:
            ann = a.annotation
            txt = ""
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                txt = ann.value
            elif ann is not None:
                txt = ast.unparse(ann) if hasattr(ast, "unparse") else ""
            if any(q in txt for q in _QUEUE_TYPES):
                self.queueish.add(a.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_name(node.value) in _QUEUE_TYPES:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.queueish.add(t.id)


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for mod in mods:
        seen: set[str] = set()

        def report(node: ast.AST, ident: str, msg: str) -> None:
            n, base = 2, ident
            while ident in seen:
                ident = f"{base}:{n}"
                n += 1
            seen.add(ident)
            findings.append(Finding(
                "CL7", mod.rel, getattr(node, "lineno", 1), ident, msg))

        _check_swallows(mod, report)
        _check_waits(mod, sym, report)
        _check_reset_handlers(mod, sym, report)
    return findings


# -- swallowed errors --------------------------------------------------------

def _check_swallows(mod: ModuleInfo, report) -> None:
    for node in mod.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _exc_names(node.type)
        if names is None:
            if not _has_raise_or_log(node.body):
                report(node, "swallow:bare",
                       "bare except: swallows SystemExit/KeyboardInterrupt "
                       "too — name the exceptions, or re-raise/log")
            continue
        broad = [n for n in names if n in _BROAD]
        if not broad:
            continue
        if _has_raise_or_log(node.body) or not _pure_swallow(node.body):
            continue
        report(node, f"swallow:{broad[0]}",
               f"except {broad[0]}: with a pure-pass body hides every "
               f"failure on this path — narrow the exception types, log "
               f"it, or # noqa: CL7 a deliberate best-effort swallow")


# -- unbounded blocking waits ------------------------------------------------

def _kw(call: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in call.keywords)


def _enclosing_classes(mod: ModuleInfo) -> list[tuple[ast.ClassDef, ast.FunctionDef]]:
    out = []
    for node in mod.walk():
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, ast.FunctionDef):
                    out.append((node, stmt))
    return out


def _class_info(sym: SymbolTable, mod: ModuleInfo,
                cls: ast.ClassDef) -> ClassInfo | None:
    return sym.classes.get(f"{mod.modname}.{cls.name}")


def _is_condition(recv: ast.expr, ci: ClassInfo | None,
                  sym: SymbolTable, modname: str) -> bool:
    """Does this receiver hold a threading.Condition?  family_locks is
    consulted directly (resolve_lock derefs Condition(self.X) aliases to
    the underlying lock, which would lose the condition kind)."""
    ch = attr_chain(recv)
    if ch and ch[0] == "self" and len(ch[1]) == 1 and ci is not None:
        li = sym.family_locks(ci).get(ch[1][0])
        if li is not None:
            return li.kind == "condition"
    li = sym.resolve_lock(recv, ci, modname)
    return li is not None and li.kind == "condition"


def _check_waits(mod: ModuleInfo, sym: SymbolTable, report) -> None:
    settimeout_cache: dict[ast.ClassDef, bool] = {}
    for cls, fn in _enclosing_classes(mod):
        ci = _class_info(sym, mod, cls)
        ctx = _FnCtx(fn)
        class_src_has_settimeout = settimeout_cache.get(cls)
        if class_src_has_settimeout is None:
            class_src_has_settimeout = any(
                isinstance(n, ast.Call) and call_name(n) == "settimeout"
                for n in ast.walk(cls))
            settimeout_cache[cls] = class_src_has_settimeout
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            recv = node.func.value
            meth = node.func.attr
            if meth == "wait" and not node.args and not node.keywords:
                if _is_condition(recv, ci, sym, mod.modname):
                    report(node, f"no-timeout:{fn.name}:wait",
                           "Condition.wait() without a timeout — a lost "
                           "notify parks this thread forever; bound it "
                           "(see osd_subop_reply_timeout)")
            elif meth == "wait_for" and len(node.args) == 1 \
                    and not _kw(node, "timeout"):
                if _is_condition(recv, ci, sym, mod.modname):
                    report(node, f"no-timeout:{fn.name}:wait_for",
                           "Condition.wait_for() without a timeout — a "
                           "lost notify or stuck predicate parks this "
                           "thread forever; bound it")
            elif meth == "get" and not node.args \
                    and not _kw(node, "timeout", "block"):
                if _queueish(recv, ctx, sym):
                    report(node, f"no-timeout:{fn.name}:queue.get",
                           "queue.get() with neither timeout nor "
                           "block=False — a producer that dies without "
                           "its sentinel parks this consumer forever")
            elif meth == "recv" and not class_src_has_settimeout:
                ch = attr_chain(recv)
                leaf = (ch[1][-1] if ch and ch[1] else ch[0] if ch else "")
                if "sock" in leaf.lower():
                    report(node, f"no-timeout:{fn.name}:recv",
                           "socket recv in a class that never calls "
                           "settimeout — an unbounded read off a dead "
                           "peer; arm a timeout on the socket")


def _queueish(recv: ast.expr, ctx: _FnCtx, sym: SymbolTable) -> bool:
    if isinstance(recv, ast.Name):
        return recv.id in ctx.queueish
    ch = attr_chain(recv)
    if ch and ch[0] == "self" and len(ch[1]) == 1:
        return sym.attr_type_index.get(ch[1][0], set()) & _QUEUE_TYPES != set()
    return False


# -- reset handlers mutating without the lock --------------------------------

def _check_reset_handlers(mod: ModuleInfo, sym: SymbolTable, report) -> None:
    for cls, fn in _enclosing_classes(mod):
        if fn.name not in _RESET_METHODS:
            continue
        ci = _class_info(sym, mod, cls)
        if ci is None or not sym.family_locks(ci):
            continue  # no owning lock exists; nothing to hold
        for stmt in fn.body:
            _walk_reset(stmt, fn, mod, report, locked=False)


def _mutates_self(node: ast.AST) -> str | None:
    """Attr name when this statement/call mutates instance state."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                return base.attr
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            ch = attr_chain(f.value)
            if ch and ch[0] == "self" and ch[1]:
                return ch[1][0]
    return None


def _walk_reset(stmt: ast.stmt, fn: ast.FunctionDef, mod: ModuleInfo,
                report, locked: bool) -> None:
    if isinstance(stmt, ast.With):
        # any with-block counts as "under a lock" — resolving which lock
        # is CL1's job; CL7 only wants mutations with NO lock at all
        for s in stmt.body:
            _walk_reset(s, fn, mod, report, locked=True)
        return
    if not locked:
        attr = _mutates_self(stmt)
        if attr is not None and not attr.startswith("__"):
            report(stmt, f"reset-race:{fn.name}:{attr}",
                   f"{fn.name} mutates self.{attr} outside any lock — "
                   f"reset callbacks run on messenger rx threads "
                   f"concurrently with dispatch; hold the owning lock")
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.stmt):
            _walk_reset(child, fn, mod, report, locked=locked)
        elif isinstance(child, ast.ExceptHandler):
            # except arms are not stmts; the error path is exactly where
            # CL7 wants to look
            for s in child.body:
                _walk_reset(s, fn, mod, report, locked=locked)
