"""CL13 — paired-resource lifecycle discipline (cephlife).

The hot path is stitched out of acquire/release pairs — Throttle
admission tickets, DevicePool buffers, the refcounted backend
sentinel, provisional trace entries, armed failpoints, started
threads, registered observers/commands, opened files.  A slot leaked
on an error path is invisible until sustained multi-tenant load pins
the throttle at its bound (the storm/autopilot setting), so CL13
proves release-on-every-path statically: each function body is walked
path-sensitively with exception edges (try/except/finally, early
returns, re-raises) over the pinned ``RESOURCE_PAIRS`` table.

Findings (idents carry no line numbers; ``<qual>`` is
``Class.method`` or the bare module-level function name):

- ``leak-on-raise:<qual>:<token>`` — a may-raise call executes while
  the token is held and NO enclosing try protects it (no ``finally``
  releasing it, no handler that releases-or-releases-then-reraises):
  the exception escapes the function with the slot still held.
- ``leak-on-return:<qual>:<token>`` — a return path (including a
  swallowing ``except ...: return``) exits with the token held in a
  function that DOES release that token on other paths.
- ``double-release:<qual>:<token>`` — a path releases a token it
  already released.
- ``release-unacquired:<qual>:<token>`` — an unconditional release in
  a function whose only acquire of that token was conditional: some
  path releases what it never took.
- ``thread-unjoined:<qual>:<name>`` — a locally-created started
  thread that is neither joined nor handed off (stored on an object /
  container, returned) before the function completes.

Ownership-transfer semantics keep the cross-function idioms quiet: a
function that acquires but never releases a token (the write
batcher's submit->wait ticket handoff, ``start()`` acquiring what
``stop()`` releases) is a TRANSFER — normal returns are fine, but
exceptional exits still leak (precisely the admission-error windows
this check exists to close).  A call passing the token with a
``donate=`` kwarg transfers it to the kernel.  ``with`` context
managers release by construction.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, attr_chain, call_name

# -- the pinned pairs table -------------------------------------------------
#
# model:
#   "count" — the token is the RECEIVER (an admission/refcount slot):
#             self._admission.get(n) .. self._admission.put(n)
#   "value" — the acquire RETURNS the resource; the token is the bound
#             name: dev = POOL.put(x) .. POOL.release(dev) / f.close()
#   "id"    — the token is the first ARGUMENT (a registry key):
#             TRACER.mark_provisional(tid) .. TRACER.promote(tid)
#   "thread"— receiver-typed Thread start/join with handoff escapes
#
# cond acquires ("get"/"get_or_fail") return bool: `if not X.get(n):
# raise` holds the token only on the fall-through path.


@dataclass(frozen=True)
class Pair:
    kind: str
    acquires: dict          # method -> "plain" | "cond"
    releases: frozenset
    model: str
    types: frozenset = frozenset()    # receiver class names
    globals: frozenset = frozenset()  # receiver module-global names
    any_recv: bool = False            # method name alone identifies it
    leak_exempt: bool = False         # no leak-on-raise/-return (CL14's
    #                                   start/stop symmetry owns these)


RESOURCE_PAIRS = (
    Pair("throttle", {"take": "plain", "get": "cond",
                      "get_or_fail": "cond"},
         frozenset({"put"}), "count", types=frozenset({"Throttle"})),
    Pair("device-pool", {"acquire": "plain", "put": "plain"},
         frozenset({"release"}), "value",
         types=frozenset({"DevicePool"}), globals=frozenset({"POOL"})),
    Pair("sentinel", {"acquire": "plain"}, frozenset({"release"}),
         "count", types=frozenset({"BackendSentinel"}),
         globals=frozenset({"SENTINEL"})),
    Pair("trace-provisional", {"mark_provisional": "plain"},
         frozenset({"promote", "discard"}), "id",
         types=frozenset({"Tracer"}), globals=frozenset({"TRACER"})),
    Pair("failpoint", {"arm": "plain"}, frozenset({"disarm"}), "id",
         types=frozenset({"FailpointRegistry"}),
         globals=frozenset({"FAILPOINTS"})),
    Pair("thread", {"start": "plain"}, frozenset({"join"}), "thread",
         types=frozenset({"Thread"})),
    Pair("conf-observer", {"add_observer": "plain"},
         frozenset({"remove_observer"}), "count", any_recv=True,
         leak_exempt=True),
    Pair("admin-command", {"register_command": "plain"},
         frozenset({"unregister_command"}), "count", any_recv=True,
         leak_exempt=True),
    Pair("file", {"open": "plain"}, frozenset({"close"}), "value"),
)

_FILE_PAIR = next(p for p in RESOURCE_PAIRS if p.kind == "file")
_THREAD_PAIR = next(p for p in RESOURCE_PAIRS if p.kind == "thread")

_ACQ_BY_METHOD: dict[str, list[Pair]] = {}
_REL_BY_METHOD: dict[str, list[Pair]] = {}
for _p in RESOURCE_PAIRS:
    for _m in _p.acquires:
        _ACQ_BY_METHOD.setdefault(_m, []).append(_p)
    for _m in _p.releases:
        _REL_BY_METHOD.setdefault(_m, []).append(_p)

# -- may-raise safelist -----------------------------------------------------
# calls that cannot realistically raise between an acquire and its
# release: pure builtins, container/str ops, clock reads, logging.
_SAFE_BUILTINS = frozenset({
    "len", "range", "min", "max", "abs", "int", "float", "str", "bool",
    "bytes", "bytearray", "list", "dict", "tuple", "set", "frozenset",
    "sorted", "reversed", "enumerate", "zip", "isinstance",
    "issubclass", "hasattr", "getattr", "setattr", "repr", "format",
    "id", "sum", "any", "all", "print", "callable", "vars", "iter",
    "divmod", "round", "hash", "super", "type", "memoryview",
})
#: bare-name calls that cannot raise (clock aliases, tracer clock)
_SAFE_NAMES = frozenset({"_monotonic", "monotonic", "trace_now",
                         "perf_counter", "time_ns"})
_SAFE_METHODS = frozenset({
    "append", "extend", "add", "discard", "clear", "keys", "values",
    "items", "setdefault", "copy", "get", "strip", "split", "lower",
    "upper", "startswith", "endswith", "format", "encode", "hex",
    "set", "is_set", "monotonic", "time", "sleep", "perf_counter",
    "notify", "notify_all", "wait", "dout", "debug", "info",
    "warning", "error", "tobytes", "count", "index", "total_seconds",
    "start", "rsplit", "splitlines", "join",
})


def _is_safe_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _SAFE_BUILTINS or f.id in _SAFE_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in _SAFE_METHODS
    return False


# -- per-function analysis --------------------------------------------------

HELD, MAYBE, OUT = "held", "maybe", "out"


@dataclass
class _Tok:
    pair: Pair
    key: str            # receiver chain / bound name / arg repr
    line: int           # acquire line
    status: str = HELD
    cond_var: str | None = None   # bool the cond-acquire bound to
    released_once: bool = False

    def clone(self) -> "_Tok":
        return _Tok(self.pair, self.key, self.line, self.status,
                    self.cond_var, self.released_once)


def _clone_state(st: dict) -> dict:
    return {k: t.clone() for k, t in st.items()}


class _TryFrame:
    def __init__(self, handlers, finalbody) -> None:
        self.handlers = handlers
        self.finalbody = finalbody
        self.exc_states: list[dict] = []


def _expr_calls(node: ast.AST):
    """Call nodes in evaluation order: arguments before the call that
    consumes them (post-order), so ``SENTINEL.acquire(Policy(...))``
    constructs the policy before the acquire takes effect."""
    for child in ast.iter_child_nodes(node):
        yield from _expr_calls(child)
    if isinstance(node, ast.Call):
        yield node


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FuncAnalysis:
    """Path-sensitive walk of one function body."""

    MAX_STATES = 24

    def __init__(self, qual: str, fn: ast.AST, attr_types: dict,
                 report) -> None:
        self.qual = qual
        self.fn = fn
        self.attr_types = attr_types      # self.<attr> -> class name
        self.local_types: dict[str, str] = {}
        self.report = report              # (finding_kind, line, token)
        self.reported: set[tuple[str, str]] = set()
        # names this function releases (transfer detection): a token
        # whose key never appears here is a handoff, not a leak
        self.released_keys: set[str] = set()
        self.acquired_keys: set[tuple[str, str]] = set()
        # names handed off ANYWHERE in the function (stored on an
        # object/container, returned): a thread registered before
        # start() is still a handoff
        self.escaped_names: set[str] = set()
        # set by _prescan when ANY call matched the resource tables; a
        # function with no matches can produce no findings, so run()
        # skips the path walk entirely (the common case, by far)
        self._interesting = False
        self._prescan()

    # -- prescan: local var types + acquire/release inventory --------------
    def _prescan(self) -> None:
        # one materialized walk: every derived inventory below iterates
        # this list instead of re-walking the tree (the function count
        # times tree size makes repeated ast.walk the scan hotspot)
        nodes = list(ast.walk(self.fn))
        known = {t for p in RESOURCE_PAIRS for t in p.types}
        # local types must be complete before the call matching below
        # (receiver resolution reads them), hence two passes over the
        # same list rather than one fused loop
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                cn = call_name(node.value)
                if cn in known:
                    self.local_types[node.targets[0].id] = cn
        # releases inside a re-raising except handler are error-path
        # COMPENSATION (release-and-reraise): they don't make the
        # normal-path handoff a "releases it on other paths" function
        comp: set[int] = set()
        for node in nodes:
            if isinstance(node, ast.Try):
                for h in node.handlers:
                    sub = [x for s in h.body for x in ast.walk(s)]
                    if any(isinstance(x, ast.Raise) for x in sub):
                        comp.update(id(x) for x in sub)
        for node in nodes:
            if isinstance(node, ast.Call):
                rel = self._match_release(node)
                if rel is not None:
                    self._interesting = True
                    if id(node) not in comp:
                        self.released_keys.add(rel[1])
                acq = self._match_acquire(node)
                if acq is not None:
                    self._interesting = True
                    if acq[2] is not None:
                        self.acquired_keys.add((acq[0].kind, acq[2]))
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add", "insert",
                                               "put", "put_nowait",
                                               "register"):
                    self.escaped_names |= {a.id for a in node.args
                                           if isinstance(a, ast.Name)}
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and any(isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in node.targets):
                self.escaped_names.add(node.value.id)
            elif isinstance(node, (ast.Return, ast.Yield)) \
                    and node.value is not None:
                self.escaped_names |= _names_in(node.value)

    # -- receiver/pair resolution ------------------------------------------
    def _recv_type(self, recv: ast.expr) -> str | None:
        if isinstance(recv, ast.Name):
            return self.local_types.get(recv.id)
        ch = attr_chain(recv)
        if ch and ch[0] == "self" and len(ch[1]) == 1:
            return self.attr_types.get(ch[1][0])
        return None

    def _recv_key(self, recv: ast.expr) -> str | None:
        if isinstance(recv, ast.Name):
            return recv.id
        ch = attr_chain(recv)
        if ch is not None:
            return ".".join((ch[0],) + tuple(ch[1]))
        return None

    def _pair_for(self, recv: ast.expr, method: str,
                  table: dict) -> Pair | None:
        for pair in table.get(method, ()):
            if pair.any_recv:
                return pair
            if isinstance(recv, ast.Name) and recv.id in pair.globals:
                return pair
            t = self._recv_type(recv)
            if t is not None and t in pair.types:
                return pair
        return None

    def _match_acquire(self, node: ast.Call):
        """(pair, mode, token_key_or_None) if this call acquires."""
        f = node.func
        if isinstance(f, ast.Name) and f.id == "open":
            return _FILE_PAIR, "plain", None  # key = the bound name
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "start" and isinstance(f.value, ast.Call) \
                and call_name(f.value) == "Thread":
            # threading.Thread(...).start() inline: unbindable
            return _THREAD_PAIR, "plain", None
        pair = self._pair_for(f.value, f.attr, _ACQ_BY_METHOD)
        if pair is None:
            return None
        mode = pair.acquires[f.attr]
        if pair.model == "count":
            key = self._recv_key(f.value)
        elif pair.model == "id":
            key = ast.unparse(node.args[0]) if node.args else None
        elif pair.model == "thread":
            key = self._recv_key(f.value)
            # attr-held threads are stop()'s to join (CL14) — only
            # track locals here
            if key is None or "." in key:
                return None
        else:  # value: key is the assignment target, filled by caller
            key = None
        if key is None and pair.model in ("count", "id", "thread"):
            return None
        return pair, mode, key

    def _match_release(self, node: ast.Call):
        """(pair, token_key) if this call releases."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        pair = self._pair_for(f.value, f.attr, _REL_BY_METHOD)
        if pair is None:
            if f.attr == "close" and isinstance(f.value, ast.Name):
                # .close() on a bare name: only pairs with a tracked
                # open-token of the same name, harmless otherwise
                return _FILE_PAIR, f.value.id
            return None
        if pair.model == "count":
            key = self._recv_key(f.value)
        elif pair.model == "id":
            key = ast.unparse(node.args[0]) if node.args else None
        elif pair.model == "thread":
            key = self._recv_key(f.value)
            if key is None or "." in key:
                return None
        else:  # value: POOL.release(tok) / tok.close()
            if f.attr == "close":
                key = self._recv_key(f.value)
            else:
                key = (node.args[0].id if node.args and
                       isinstance(node.args[0], ast.Name) else None)
        if key is None:
            return None
        return pair, key

    # -- findings ----------------------------------------------------------
    def _emit(self, kind: str, line: int, tok_key: str,
              msg: str) -> None:
        if (kind, tok_key) in self.reported:
            return
        self.reported.add((kind, tok_key))
        self.report(kind, line, tok_key, msg)

    def _leak_on_raise(self, st: dict, line: int,
                       frames: list[_TryFrame], what: str) -> None:
        """A call at `line` may raise: every held token whose release
        no enclosing frame guarantees leaks out of the function."""
        escapes = all(not fr.handlers for fr in frames)
        if not escapes:
            return  # a handler will see the state (simulated below)
        for tok in st.values():
            if tok.status != HELD or tok.pair.leak_exempt \
                    or tok.pair.model == "thread":
                continue
            if any(self._releases_key(fr.finalbody, tok)
                   for fr in frames):
                continue
            self._emit(
                "leak-on-raise", line, tok.key,
                f"{tok.pair.kind} '{tok.key}' acquired at line "
                f"{tok.line} is still held when '{what}' may raise — "
                f"the exception escapes {self.qual}() with the slot "
                f"leaked (wrap in try/finally or release-and-reraise)")

    def _releases_key(self, stmts, tok: _Tok) -> bool:
        for s in stmts:
            for node in ast.walk(s):
                if isinstance(node, ast.Call):
                    rel = self._match_release(node)
                    if rel and rel[0].kind == tok.pair.kind \
                            and rel[1] == tok.key:
                        return True
        return False

    def _check_exit(self, st: dict, line: int, why: str,
                    frames: list[_TryFrame] = ()) -> None:
        """A return (or fall-off-end) with tokens held: leak unless
        the token is a cross-function handoff (never released here)
        or an enclosing finally releases it on the way out."""
        for tok in st.values():
            if tok.status != HELD or tok.pair.leak_exempt:
                continue
            if any(self._releases_key(fr.finalbody, tok)
                   for fr in frames):
                continue
            if tok.pair.model == "thread":
                if tok.key in self.escaped_names:
                    continue  # handed off somewhere in this function
                self._emit(
                    "thread-unjoined", tok.line, tok.key,
                    f"thread '{tok.key}' started at line {tok.line} in "
                    f"{self.qual}() is never joined or handed off")
                continue
            if tok.key not in self.released_keys:
                continue  # handoff: the paired release lives elsewhere
            self._emit(
                "leak-on-return", line, tok.key,
                f"{tok.pair.kind} '{tok.key}' acquired at line "
                f"{tok.line} is still held on the {why} at line "
                f"{line} though {self.qual}() releases it on other "
                f"paths")

    # -- the walk ----------------------------------------------------------
    def run(self) -> None:
        if not self._interesting:
            return  # no resource call anywhere: no finding can fire
        body = getattr(self.fn, "body", [])
        out = self._block(body, [{}], [])
        last = body[-1].end_lineno if body else self.fn.lineno
        for st in out:
            self._check_exit(st, last, "fall-through exit")

    def _dedup(self, states: list[dict]) -> list[dict]:
        seen, out = set(), []
        for st in states:
            key = tuple(sorted((k, t.status) for k, t in st.items()))
            if key not in seen:
                seen.add(key)
                out.append(st)
        return out[: self.MAX_STATES]

    def _block(self, stmts, states: list[dict],
               frames: list[_TryFrame]) -> list[dict]:
        for stmt in stmts:
            states = self._dedup(
                [s for st in states for s in self._stmt(stmt, st, frames)])
            if not states:
                break
        return states

    def _stmt(self, stmt, st: dict,
              frames: list[_TryFrame]) -> list[dict]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return [st]  # nested defs are their own analysis scope
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_exprs(stmt.value, st, frames)
                self._escape_targets(stmt.value, st)
            self._check_exit(st, stmt.lineno, "return", frames)
            return []
        if isinstance(stmt, ast.Raise):
            # an explicit raise escapes like a may-raise call
            self._leak_on_raise(st, stmt.lineno, frames, "raise")
            if frames:
                frames[-1].exc_states.append(_clone_state(st))
            return []
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return []  # approximated: path rejoins after the loop
        if isinstance(stmt, ast.If):
            return self._if(stmt, st, frames)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, st, frames)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._loop(stmt, st, frames)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                # `with open(...) as f` releases by construction; scan
                # the context exprs for other effects
                self._scan_exprs(item.context_expr, st, frames,
                                 managed=True)
            return self._block(stmt.body, [st], frames)
        if isinstance(stmt, ast.Assign):
            return [self._assign(stmt, st, frames)]
        if isinstance(stmt, ast.AugAssign):
            self._scan_exprs(stmt.value, st, frames)
            return [st]
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            fake = ast.Assign(targets=[stmt.target], value=stmt.value)
            ast.copy_location(fake, stmt)
            return [self._assign(fake, st, frames)]
        if isinstance(stmt, ast.Expr):
            self._scan_exprs(stmt.value, st, frames)
            return [st]
        if isinstance(stmt, (ast.Assert, ast.Delete)):
            for v in ast.iter_child_nodes(stmt):
                if isinstance(v, ast.expr):
                    self._scan_exprs(v, st, frames)
            return [st]
        return [st]

    # -- expression effects ------------------------------------------------
    def _scan_exprs(self, expr: ast.expr, st: dict,
                    frames: list[_TryFrame], managed: bool = False,
                    bind: str | None = None) -> None:
        """Apply acquire/release/may-raise effects of every call in
        `expr`, in source order.  `bind` names the assignment target
        for value-model acquires; `managed` marks a `with` context."""
        for node in _expr_calls(expr):
            acq = self._match_acquire(node)
            if acq is not None:
                pair, mode, key = acq
                if pair.model == "value" and key is None:
                    key = bind
                if managed and pair.model == "value":
                    continue  # the context manager releases it
                if key is None:
                    if pair.model == "thread":
                        # Thread(...).start() inline: fire-and-forget
                        self._emit(
                            "thread-unjoined", node.lineno,
                            f"anon@{node.lineno}",
                            f"thread started inline at line "
                            f"{node.lineno} in {self.qual}() can never "
                            f"be joined (bind it, or noqa the "
                            f"fire-and-forget)")
                        continue
                    key = f"anon@{node.lineno}"
                tok = _Tok(pair, key, node.lineno)
                if mode == "cond":
                    tok.cond_var = bind
                st[key] = tok
                continue
            rel = self._match_release(node)
            if rel is not None:
                pair, key = rel
                tok = st.get(key)
                if tok is None or tok.pair.kind != pair.kind:
                    # released here but not held on THIS path: if this
                    # function DID acquire it (conditionally, on some
                    # other path) and the release is unconditional,
                    # some path releases what it never took; with no
                    # in-function acquire it's a cross-function
                    # release — not ours to judge
                    if (pair.kind, key) in self.acquired_keys \
                            and not getattr(node, "_cl13_guard_names",
                                            None):
                        self._emit(
                            "release-unacquired", node.lineno, key,
                            f"{pair.kind} '{key}' released "
                            f"unconditionally at line {node.lineno} "
                            f"in {self.qual}() but only acquired "
                            f"under a condition — some path releases "
                            f"what it never took")
                    continue
                if tok.status == OUT:
                    if not self._guarded(node, key):
                        self._emit(
                            "double-release", node.lineno, key,
                            f"{pair.kind} '{key}' released again at "
                            f"line {node.lineno} in {self.qual}() — "
                            f"already released on this path")
                else:
                    tok.status = OUT
                    tok.released_once = True
                continue
            # handing a token to a container/queue transfers ownership
            if isinstance(node.func, ast.Attribute) and node.func.attr \
                    in ("append", "add", "insert", "put",
                        "put_nowait"):
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in st \
                            and st[a.id].pair.model in ("value",
                                                        "thread"):
                        st[a.id].status = OUT
                continue
            # donation: passing a held value token with donate=<expr>
            don = next((kw for kw in node.keywords
                        if kw.arg == "donate"), None)
            if don is not None:
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in st:
                        tok = st[a.id]
                        if tok.pair.model == "value":
                            lit = isinstance(don.value, ast.Constant)
                            tok.status = OUT if (
                                lit and don.value.value) else (
                                MAYBE if not lit else tok.status)
            if not _is_safe_call(node):
                self._leak_on_raise(
                    st, node.lineno, frames,
                    call_name(node) or ast.unparse(node.func))
                if frames:
                    frames[-1].exc_states.append(_clone_state(st))

    def _guarded(self, node: ast.Call, key: str) -> bool:
        """Release under an `if` that tests the token itself
        (``if dev is not shards: POOL.release(dev)``) correlates with
        a conditional acquire — assume the guard is right."""
        guard = getattr(node, "_cl13_guard_names", None)
        return guard is not None and (key in guard
                                      or key.split(".")[-1] in guard)

    def _escape_targets(self, expr: ast.expr, st: dict) -> None:
        """Returning/yielding a token hands ownership to the caller."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in st:
                tok = st[n.id]
                if tok.pair.model in ("value", "thread"):
                    tok.status = OUT

    # -- structured statements ---------------------------------------------
    def _assign(self, stmt: ast.Assign, st: dict,
                frames: list[_TryFrame]) -> dict:
        bind = None
        managed = False
        if len(stmt.targets) == 1 and isinstance(stmt.targets[0],
                                                 ast.Name):
            bind = stmt.targets[0].id
        elif any(isinstance(t, (ast.Attribute, ast.Subscript))
                 for t in stmt.targets):
            # `self._dev = open(...)`: stored on an object, the
            # lifetime outlives this function (CL14's territory)
            managed = True
        self._scan_exprs(stmt.value, st, frames, bind=bind,
                         managed=managed)
        # storing a held token on an object/container is a handoff
        if isinstance(stmt.value, ast.Name) and stmt.value.id in st:
            tgt = stmt.targets[0]
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                tok = st[stmt.value.id]
                if tok.pair.model in ("value", "thread"):
                    tok.status = OUT
        return st

    def _if(self, stmt: ast.If, st: dict,
            frames: list[_TryFrame]) -> list[dict]:
        then_st = _clone_state(st)
        else_st = st
        cond_names = _names_in(stmt.test)
        # condition effects (an acquire inside the test itself)
        direct = self._cond_acquire_in_test(stmt.test, then_st, else_st,
                                            frames)
        if not direct:
            self._scan_exprs(stmt.test, else_st, frames)
            then_st = _clone_state(else_st)
            # `if not ok:` / `if ok:` resolving a cond-acquire bool
            self._apply_bool_guard(stmt.test, then_st, else_st)
        # tag releases under this test with the guard names so
        # `if dev is not x: POOL.release(dev)` correlates
        for branch in (stmt.body, stmt.orelse):
            for s in branch:
                for node in ast.walk(s):
                    if isinstance(node, ast.Call):
                        node._cl13_guard_names = cond_names | getattr(
                            node, "_cl13_guard_names", set())
        out = self._block(stmt.body, [then_st], frames)
        out += self._block(stmt.orelse, [else_st], frames)
        # guard-correlated merge: a token the test mentions that one
        # branch released counts as released (the guard tracked the
        # conditional acquire)
        released = {k for s in out for k, t in s.items()
                    if t.status == OUT and (k in cond_names or
                                            k.split(".")[-1] in
                                            cond_names)}
        for s in out:
            for k in released:
                if k in s:
                    s[k].status = OUT
        return out

    def _cond_acquire_in_test(self, test: ast.expr, then_st: dict,
                              else_st: dict,
                              frames: list[_TryFrame]) -> bool:
        """``if X.get(n):`` / ``if not X.get(n):`` — the token exists
        only on the truthy/falsy side respectively."""
        positive, call = True, test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            positive, call = False, test.operand
        if not isinstance(call, ast.Call):
            return False
        acq = self._match_acquire(call)
        if acq is None or acq[1] != "cond":
            return False
        pair, _mode, key = acq
        tok = _Tok(pair, key, call.lineno)
        (then_st if positive else else_st)[key] = tok
        return True

    def _apply_bool_guard(self, test: ast.expr, then_st: dict,
                          else_st: dict) -> None:
        positive, name = True, test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            positive, name = False, test.operand
        if not isinstance(name, ast.Name):
            return
        for st, holds in ((then_st, positive), (else_st, not positive)):
            for tok in list(st.values()):
                if tok.cond_var == name.id and not holds:
                    del st[tok.key]

    def _try(self, stmt: ast.Try, st: dict,
             frames: list[_TryFrame]) -> list[dict]:
        frame = _TryFrame(stmt.handlers, stmt.finalbody)
        normal = self._block(stmt.body, [st], frames + [frame])
        out = self._block(stmt.orelse, normal, frames) if stmt.orelse \
            else normal
        # exception edges: every may-raise snapshot flows into each
        # handler; a handler that neither releases nor re-raises and
        # then returns is a swallowed-leak return path
        exc = self._dedup(frame.exc_states)
        # a handler that re-raises still runs THIS try's finally on the
        # way out — handler bodies see a finally-only frame
        hframes = (frames + [_TryFrame([], stmt.finalbody)]
                   if stmt.finalbody else frames)
        for handler in stmt.handlers:
            out += self._block(handler.body,
                               [_clone_state(s) for s in exc], hframes)
        if stmt.finalbody:
            out = self._block(stmt.finalbody, self._dedup(out), frames)
            # tokens escaping exceptionally still run the finally
            if not stmt.handlers and exc:
                self._block(stmt.finalbody,
                            [_clone_state(s) for s in exc], frames)
        return out

    def _loop(self, stmt, st: dict,
              frames: list[_TryFrame]) -> list[dict]:
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_exprs(stmt.iter, st, frames)
        else:
            self._scan_exprs(stmt.test, st, frames)
        once = self._block(stmt.body, [_clone_state(st)], frames)
        out = [st] + once  # zero or one-plus iterations
        if stmt.orelse:
            out = self._block(stmt.orelse, self._dedup(out), frames)
        return self._dedup(out)


# -- module driver ----------------------------------------------------------

def _functions(mod: ModuleInfo):
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt.name, None, stmt
        elif isinstance(stmt, ast.ClassDef):
            for s in stmt.body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{stmt.name}.{s.name}", stmt.name, s


def check(mods: list[ModuleInfo], sym: SymbolTable,
          cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[str, str, str]] = set()
    for mod in mods:
        if mod.rel.startswith("qa/analyzer/"):
            continue  # the analyzer's own tables mention the pair names
        for qual, clsname, fn in _functions(mod):
            attr_types: dict[str, str] = {}
            if clsname is not None:
                ci = next((c for c in sym.class_by_name.get(clsname, ())
                           if c.path == mod.rel), None)
                if ci is not None:
                    attr_types = sym.family_attr_types(ci)

            def report(kind, line, tok, msg, _mod=mod, _qual=qual):
                ident = f"{kind}:{_qual}:{tok}"
                k = ("CL13", _mod.rel, ident)
                if k not in seen:
                    seen.add(k)
                    findings.append(
                        Finding("CL13", _mod.rel, line, ident, msg))

            _FuncAnalysis(qual, fn, attr_types, report).run()
    return findings
