"""CL6 — wire-protocol conformance for the @register_message family.

The five hand-paired message modules (msg/message.py, mon/messages.py,
osd/messages.py, fs/messages.py, mgr/messages.py) are the highest-risk
drift surface in the package: `encode_payload` and `decode_payload` are
written by hand, twice, and nothing ties them together until a peer
fails to parse a frame.  Four sub-checks:

- ``encdec-*``      symbolic execution of encode_payload (the ordered
  ``append_*`` calls on the BufferList parameter) against decode_payload
  (the ordered ``get_*`` calls on the iterator parameter).  A count
  mismatch, a width/kind mismatch at position k, or a class defining
  only half the pair is a wire break the first cross-version peer hits.
  Non-linear bodies (branches/loops/helper calls) are skipped — the
  dynamic round-trip test (tests/test_analyzer_proto.py) covers what
  straight-line symbolic execution can't prove.
- ``field-loss:*``  an attribute assigned in ``__init__`` that the
  effective encode path (``self.X`` reads in encode_payload + the FIELDS
  tuple of JSON-bodied messages) never serializes: the field silently
  dies on the wire and resurrects as the constructor default.
- ``field-shadow:*``  a FIELDS entry named after a framing attribute
  (``seq``/``src``).  send_message stamps both on the instance BEFORE
  encode_payload runs, so the payload silently carries the connection
  sequence instead of the protocol value — the bug that killed the MDS
  cap-revoke staleness gate until the round-trip test caught it.
- ``dup-type:*``    two registered classes sharing a MSG_TYPE code.
  register_message raises at import time ONLY if both modules are
  imported into one process — a client importing mon/messages and a
  gateway importing osd/messages never see the collision; the analyzer
  sees every module at once.  ``no-type:*`` flags a registered class
  that never sets MSG_TYPE (it would shadow the base's 0).
- ``unhandled:*`` / ``unsent-handler:*``  dispatch reachability: a
  message type constructed in the package with no ``isinstance`` arm
  anywhere in a dispatcher's ms_dispatch chain is sent into the void
  (the messenger drops it after every dispatcher returns False); an
  isinstance arm for a type nothing constructs is dead protocol.

Width map: append_u8/u16/u32/u64 pair with get_u8/..64; append_str with
get_str or get_str_bytes (same u32-length framing); raw ``append`` with
``get_bytes``.  ``append_zero`` pairs with ``get_bytes`` too.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, call_name

# encode-call name -> wire token; decode-call name -> wire token
_ENC_TOKENS = {
    "append_u8": "u8", "append_u16": "u16", "append_u32": "u32",
    "append_u64": "u64", "append_str": "str", "append": "raw",
    "append_zero": "raw",
}
_DEC_TOKENS = {
    "get_u8": "u8", "get_u16": "u16", "get_u32": "u32", "get_u64": "u64",
    "get_str": "str", "get_str_bytes": "str", "get_bytes": "raw",
}
# attrs the base Message/framing owns; subclasses never encode them
_FRAMING_ATTRS = {"seq", "src"}
_SENDISH = ("send_message", "send_mon", "send_to", "_forward_to_leader")


@dataclass
class MsgClass:
    name: str
    module: str
    path: str
    line: int
    node: ast.ClassDef
    bases: list[str]
    registered: bool = False
    msg_type: int | None = None           # own (not inherited) MSG_TYPE
    fields: tuple[str, ...] | None = None  # own FIELDS tuple
    encode: ast.FunctionDef | None = None
    decode: ast.FunctionDef | None = None
    init: ast.FunctionDef | None = None


@dataclass
class ProtoIndex:
    classes: dict[str, MsgClass] = field(default_factory=dict)
    constructed: dict[str, list[tuple[str, int]]] = field(default_factory=dict)
    handled: dict[str, list[tuple[str, int]]] = field(default_factory=dict)
    sent: dict[str, list[tuple[str, int]]] = field(default_factory=dict)


def _is_register_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Name):
        return dec.id == "register_message"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "register_message"
    if isinstance(dec, ast.Call):
        return _is_register_decorator(dec.func)
    return False


def _scan_class(mod: ModuleInfo, node: ast.ClassDef) -> MsgClass:
    bases = []
    for b in node.bases:
        if isinstance(b, ast.Name):
            bases.append(b.id)
        elif isinstance(b, ast.Attribute):
            bases.append(b.attr)
    mc = MsgClass(name=node.name, module=mod.modname, path=mod.rel,
                  line=node.lineno, node=node, bases=bases,
                  registered=any(_is_register_decorator(d)
                                 for d in node.decorator_list))
    for stmt in node.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = stmt.targets[0].id
            if tgt == "MSG_TYPE" and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, int):
                mc.msg_type = stmt.value.value
            elif tgt == "FIELDS" and isinstance(stmt.value, ast.Tuple):
                vals = []
                for e in stmt.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        vals.append(e.value)
                mc.fields = tuple(vals)
        elif isinstance(stmt, ast.FunctionDef):
            if stmt.name == "encode_payload":
                mc.encode = stmt
            elif stmt.name == "decode_payload":
                mc.decode = stmt
            elif stmt.name == "__init__":
                mc.init = stmt
    return mc


def build_index(mods: list[ModuleInfo]) -> ProtoIndex:
    idx = ProtoIndex()
    # pass 1: classes (so pass 2 knows the registered names)
    for mod in mods:
        for node in mod.walk():
            if isinstance(node, ast.ClassDef):
                mc = _scan_class(mod, node)
                # keep the first definition; message classes are unique
                idx.classes.setdefault(mc.name, mc)
    reg_names = {n for n, mc in idx.classes.items()
                 if _is_message(idx, mc)}
    for mod in mods:
        _scan_usage(idx, mod, reg_names)
    return idx


def _is_message(idx: ProtoIndex, mc: MsgClass) -> bool:
    """Registered itself, or an ancestor of a registered class — the
    chain walk below needs base classes like _JsonMessage/Message too."""
    if mc.registered:
        return True
    return any(c.registered and mc.name in _ancestry(idx, c)
               for c in idx.classes.values())


def _ancestry(idx: ProtoIndex, mc: MsgClass, limit: int = 8) -> list[str]:
    """Base-class name chain (nearest first), package-local names only."""
    out: list[str] = []
    cur = mc
    while limit > 0:
        limit -= 1
        nxt = None
        for b in cur.bases:
            if b in idx.classes and b not in out and b != mc.name:
                nxt = idx.classes[b]
                break
        if nxt is None:
            break
        out.append(nxt.name)
        cur = nxt
    return out


def _chain(idx: ProtoIndex, mc: MsgClass) -> list[MsgClass]:
    return [mc] + [idx.classes[n] for n in _ancestry(idx, mc)]


def _effective(idx: ProtoIndex, mc: MsgClass, attr: str):
    for c in _chain(idx, mc):
        v = getattr(c, attr)
        if v is not None:
            return c, v
    return None, None


def _scan_usage(idx: ProtoIndex, mod: ModuleInfo, reg: set[str]) -> None:
    """Construction sites, isinstance arms, and construction->send flows."""
    for node in mod.walk():
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in reg and not isinstance(node.func, ast.Attribute):
                # plain Name call = construction (attribute calls are
                # methods that happen to share a name)
                idx.constructed.setdefault(cn, []).append(
                    (mod.rel, node.lineno))
            if cn == "isinstance" and len(node.args) == 2:
                t = node.args[1]
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    name = e.id if isinstance(e, ast.Name) else (
                        e.attr if isinstance(e, ast.Attribute) else None)
                    if name in reg:
                        idx.handled.setdefault(name, []).append(
                            (mod.rel, node.lineno))
        if isinstance(node, ast.FunctionDef):
            _scan_send_flow(idx, mod, node, reg)


def _scan_send_flow(idx: ProtoIndex, mod: ModuleInfo,
                    fn: ast.FunctionDef, reg: set[str]) -> None:
    """Within one function: MFoo(...) passed to a send-ish call directly,
    or assigned to a name later passed to one (no order sensitivity —
    good enough for flow in straight-line send helpers)."""
    assigned: dict[str, str] = {}   # var -> message class
    returned: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cn = call_name(node.value)
            if cn in reg and not isinstance(node.value.func, ast.Attribute):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assigned[t.id] = cn
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Call):
            cn = call_name(node.value)
            if cn in reg and not isinstance(node.value.func, ast.Attribute):
                returned.add(cn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn not in _SENDISH:
            continue
        for a in node.args:
            if isinstance(a, ast.Call):
                acn = call_name(a)
                if acn in reg and not isinstance(a.func, ast.Attribute):
                    idx.sent.setdefault(acn, []).append(
                        (mod.rel, node.lineno))
            elif isinstance(a, ast.Name) and a.id in assigned:
                idx.sent.setdefault(assigned[a.id], []).append(
                    (mod.rel, node.lineno))
    # a message built and returned from a _handle/_make helper is sent by
    # the caller; count it as sent rather than chase inter-procedural flow
    for cn in returned:
        idx.sent.setdefault(cn, []).append((mod.rel, fn.lineno))


# -- symbolic encode/decode execution ---------------------------------------

def _payload_param(fn: ast.FunctionDef) -> str | None:
    args = [a.arg for a in fn.args.args]
    return args[1] if len(args) >= 2 else None


def _wire_ops(fn: ast.FunctionDef, tokens: dict[str, str]
              ) -> tuple[list[tuple[str, int]], bool]:
    """Ordered (token, line) wire ops on the payload parameter; second
    element False when the body is non-linear (branch/loop/try or a
    helper call that receives the payload object) and the sequence is
    therefore untrustworthy."""
    param = _payload_param(fn)
    if param is None:
        return [], False
    linear = True
    ops: list[tuple[str, int]] = []

    def receiver_is_param(call: ast.Call) -> bool:
        f = call.func
        return isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Name) and f.value.id == param

    raw: list[tuple[int, int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.For, ast.While, ast.Try,
                             ast.With, ast.IfExp)):
            linear = False
        if isinstance(node, ast.Call):
            if receiver_is_param(node):
                cn = call_name(node)
                if cn in tokens:
                    raw.append((node.lineno, node.col_offset, tokens[cn]))
                else:
                    linear = False  # unknown method on the payload object
            elif any(isinstance(a, ast.Name) and a.id == param
                     for a in node.args):
                linear = False      # payload escapes into a helper
    # ast.walk is breadth-first; wire order is SOURCE order, so sort by
    # position (a call nested inside int(...) must not float to the end)
    ops = [(tok, line) for line, _col, tok in sorted(raw)]
    return ops, linear


def _init_attrs(fn: ast.FunctionDef) -> dict[str, int]:
    out: dict[str, int] = {}
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.setdefault(t.attr, t.lineno)
    return out


def _self_attr_reads(fn: ast.FunctionDef) -> set[str]:
    return {n.attr for n in ast.walk(fn)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name) and n.value.id == "self"}


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    idx = build_index(mods)
    findings: list[Finding] = []
    msg_classes = {n: mc for n, mc in idx.classes.items()
                   if _is_message(idx, mc)}
    registered = {n: mc for n, mc in msg_classes.items() if mc.registered}

    # -- encode/decode pairing (per defining class, registered or base) ----
    for name, mc in sorted(msg_classes.items()):
        if mc.encode is None and mc.decode is None:
            continue
        if (mc.encode is None) != (mc.decode is None):
            half = "encode_payload" if mc.encode is not None \
                else "decode_payload"
            fn = mc.encode or mc.decode
            findings.append(Finding(
                "CL6", mc.path, fn.lineno, f"encdec-half:{name}",
                f"{name} defines {half} but not its pair — the inherited "
                f"half decodes a different wire layout"))
            continue
        enc, enc_ok = _wire_ops(mc.encode, _ENC_TOKENS)
        dec, dec_ok = _wire_ops(mc.decode, _DEC_TOKENS)
        if not (enc_ok and dec_ok):
            continue  # non-linear: the dynamic round-trip test owns it
        if len(enc) != len(dec):
            findings.append(Finding(
                "CL6", mc.path, mc.encode.lineno, f"encdec-count:{name}",
                f"{name}.encode_payload writes {len(enc)} wire field(s) "
                f"but decode_payload reads {len(dec)} — a peer decoding "
                f"this frame desyncs"))
            continue
        for k, ((et, eline), (dt, _dl)) in enumerate(zip(enc, dec)):
            if et != dt:
                findings.append(Finding(
                    "CL6", mc.path, eline, f"encdec-order:{name}:{k}",
                    f"{name} wire field {k} encoded as {et} but decoded "
                    f"as {dt} — order/width mismatch desyncs the frame"))
                break

    # -- field loss --------------------------------------------------------
    for name, mc in sorted(registered.items()):
        init_cls, init = _effective(idx, mc, "init")
        if init is None or init_cls is None:
            continue
        if init_cls.name != name and init_cls.fields is not None:
            # inherits the FIELDS-driven __init__ (sets exactly FIELDS)
            continue
        _fc, fields = _effective(idx, mc, "fields")
        enc_cls, enc = _effective(idx, mc, "encode")
        encoded: set[str] = set(fields or ())
        if enc is not None:
            encoded |= _self_attr_reads(enc)
        if enc is None and fields is None:
            continue  # nothing encodes anything (abstract base)
        for attr, line in sorted(_init_attrs(init).items()):
            if attr in _FRAMING_ATTRS or attr.startswith("_"):
                continue
            if attr not in encoded:
                findings.append(Finding(
                    "CL6", init_cls.path, line, f"field-loss:{name}.{attr}",
                    f"{name}.__init__ sets self.{attr} but "
                    f"{enc_cls.name if enc_cls else name}.encode_payload "
                    f"never serializes it — the field silently resets to "
                    f"its default across the wire"))

    # -- framing-attr shadowing --------------------------------------------
    for name, mc in sorted(msg_classes.items()):
        if mc.fields is None:
            continue
        for attr in mc.fields:
            if attr in _FRAMING_ATTRS:
                findings.append(Finding(
                    "CL6", mc.path, mc.line, f"field-shadow:{name}.{attr}",
                    f"{name}.FIELDS contains {attr!r}, which send_message "
                    f"stamps with the CONNECTION value before the payload "
                    f"encodes — the protocol field is silently clobbered "
                    f"on the wire; rename it"))

    # -- duplicate / missing MSG_TYPE --------------------------------------
    by_code: dict[int, list[MsgClass]] = {}
    for name, mc in sorted(registered.items()):
        code = None
        for c in _chain(idx, mc):
            if c.msg_type is not None:
                code = c.msg_type
                break
        if code is None or code == 0:
            findings.append(Finding(
                "CL6", mc.path, mc.line, f"no-type:{name}",
                f"registered message {name} never sets a nonzero MSG_TYPE "
                f"— it shadows the base type code in the registry"))
            continue
        by_code.setdefault(code, []).append(mc)
    for code, group in sorted(by_code.items()):
        if len(group) > 1:
            names = ", ".join(m.name for m in group)
            for m in group[1:]:
                findings.append(Finding(
                    "CL6", m.path, m.line, f"dup-type:{code}",
                    f"MSG_TYPE {code} registered by multiple classes "
                    f"({names}) — whichever module imports second raises "
                    f"(or worse, never co-imports and misdecodes)"))

    # -- dispatch reachability ---------------------------------------------
    for name, mc in sorted(registered.items()):
        sent = idx.sent.get(name, [])
        handled = idx.handled.get(name, [])
        constructed = idx.constructed.get(name, [])
        if sent and not handled:
            path, line = sent[0]
            findings.append(Finding(
                "CL6", path, line, f"unhandled:{name}",
                f"{name} is sent here but no dispatcher's ms_dispatch "
                f"chain has an isinstance arm for it — the messenger "
                f"drops it on the floor"))
        if handled and not constructed:
            path, line = handled[0]
            findings.append(Finding(
                "CL6", path, line, f"unsent-handler:{name}",
                f"dispatcher handles {name} but nothing in the package "
                f"constructs one — dead protocol arm (or the sender was "
                f"lost in a refactor)"))
    return findings
