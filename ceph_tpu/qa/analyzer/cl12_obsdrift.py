"""CL12 — observability drift.

The CL4/CL5 shape (N surfaces share a name vocabulary verbatim;
nothing enforces agreement) generalized to the whole observability
plane.  Six inventories are reconciled statically:

- **perf counters** — names declared on a PerfCounters builder/duck
  vs names mutated through a perf-ish receiver:
  ``ctr-undeclared:<name>`` (mutation of a name nothing declares —
  KeyError on that path at runtime) and ``ctr-unused:<name>``
  (declared, never mutated, never mentioned elsewhere — a series that
  can only ever render zero).
- **tracepoints** — ``tracepoint("subsys", "event", ...)`` literals vs
  the tracer's KNOWN_TRACEPOINTS catalogue vs the tracing docs table:
  ``tp-unknown:``/``tp-orphan:``/``tp-undoc:``/``tp-orphan-doc:``.
- **health checks** — ``checks[NAME] = ...`` raise sites vs the bold
  check names in the observability doc: ``health-undoc:`` /
  ``health-orphan-doc:``, plus ``health-unconditional:`` for a raise
  with no enclosing condition — a check that can never clear
  (raise-and-clear symmetry is a storm invariant; this makes it
  static).
- **commands** — mon/asok command names SENT (dict literals carrying
  the routing key) vs dispatch arms (equality/membership/startswith
  tests on the routing variable) vs admin-socket registrations:
  ``cmd-unhandled:<name>`` (sent, no arm matches — the wire-dead
  class) and ``cmd-unsent:<name>`` (an arm no tool can reach — dead
  dispatch the CLI never grew a word-form for); registered admin
  commands missing from the docs are ``asok-undoc:<name>``.
- **stages** — histogram declarations against the tracer's stage
  tuples and both docs: ``stage-unknown:`` (a histogram outside the
  taxonomy), ``stage-nohist:`` (a stage with no histogram),
  ``stage-undoc:``.
- **exported series** — full literal series names in code vs the
  series tokens in the docs (a trailing ``*``/``_`` token documents a
  family): ``series-undoc:<name>``.

Idents carry the drifting NAME, never a line, so baseline entries
survive edits.  Families whose source of truth (tracer file, docs) is
absent are skipped — fixture trees stay quiet unless they opt in.
"""
from __future__ import annotations

import ast
import re

from .core import Config, Finding, ModuleInfo, parse_source, read_doc, rel_of
from .symbols import SymbolTable, attr_chain, call_name

_DECL_METHODS = {"add_u64_counter", "add_u64", "add_time", "add_time_avg",
                 "add_time_histogram", "_add"}
_MUT_METHODS = {"inc", "dec", "set", "tinc", "avg", "hinc", "bump"}
#: receiver spellings that make an inc()/set() a perf mutation rather
#: than an arbitrary method call (OSD.logger is upstream's name for its
#: PerfCounters; the rest are the package's duck-typed holders)
_PERF_RECEIVERS = {"logger", "_logger", "perf", "_perf", "pc", "_pc",
                   "counters", "_counters", "accounting", "_accounting"}

_HEALTH_NAME_RE = re.compile(r"[A-Z][A-Z0-9_]{2,}")
_HEALTH_DOC_RE = re.compile(r"\*\*([A-Z][A-Z0-9_]{2,})\*\*")
_SERIES_RE = re.compile(r"ceph_[a-z0-9][a-z0-9_]*")
_SERIES_DOC_RE = re.compile(r"ceph_[a-z0-9_]+\*?")
_DOC_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.\- ]+)`\s*\|")

_STAGE_TUPLES = ("OP_STAGES", "BG_STAGES", "READ_STAGES")


def parse_tracer_inventory(path) -> dict[str, tuple[set[str], int]]:
    """KNOWN_TRACEPOINTS + the stage tuples from the tracer module, each
    as (names, declaration line)."""
    tree, _lines = parse_source(path)
    out: dict[str, tuple[set[str], int]] = {}
    wanted = set(_STAGE_TUPLES) | {"KNOWN_TRACEPOINTS"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = [t.id for t in targets
                 if isinstance(t, ast.Name) and t.id in wanted]
        if not names:
            continue
        if isinstance(value, ast.Call):  # frozenset((...))
            value = value.args[0] if value.args else value
        elts: list[ast.expr] = []
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            elts = value.elts
        vals = {e.value for e in elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
        for n in names:
            out[n] = (vals, node.lineno)
    return out


def _receiver_last(node: ast.Call) -> str | None:
    if not isinstance(node.func, ast.Attribute):
        return None
    ch = attr_chain(node.func.value)
    if ch is None:
        return None
    base, attrs = ch
    return attrs[-1] if attrs else base


def _first_arg(node: ast.Call):
    """(literal-name, fstring-prefix) — exactly one is non-None for a
    usable arg, both None otherwise."""
    if not node.args:
        return None, None
    a0 = node.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return a0.value, None
    if isinstance(a0, ast.JoinedStr) and a0.values:
        v0 = a0.values[0]
        if isinstance(v0, ast.Constant) and isinstance(v0.value, str) \
                and v0.value:
            return None, v0.value
    return None, None


def _health_raises(tree: ast.AST):
    """(name, line, conditional) for ``checks[NAME] = ...`` sites."""
    out: list[tuple[str, int, bool]] = []

    def rec(stmts, cond: bool) -> None:
        for s in stmts:
            if isinstance(s, ast.Assign):
                for t in s.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in ("checks", "health_checks") \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str) \
                            and _HEALTH_NAME_RE.fullmatch(t.slice.value):
                        out.append((t.slice.value, s.lineno, cond))
            branches = isinstance(s, (ast.If, ast.While, ast.For,
                                      ast.AsyncFor, ast.Try))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub:
                    rec(sub, cond or branches)
            for h in getattr(s, "handlers", ()):
                rec(h.body, True)

    rec(tree.body, False)
    return out


def check(mods: list[ModuleInfo], sym: SymbolTable,
          cfg: Config) -> list[Finding]:
    findings: list[Finding] = []

    # ---- one pass over every module: collect all six inventories ------
    ctr_decl: dict[str, tuple[str, int]] = {}
    ctr_decl_pref: set[str] = set()
    ctr_mut: dict[str, tuple[str, int]] = {}
    ctr_mut_pref: set[str] = set()
    hist_decl: dict[str, tuple[str, int]] = {}
    tp_sites: dict[str, tuple[str, int]] = {}
    raises: list[tuple[str, str, int, bool]] = []  # name, rel, line, cond
    sent: dict[str, tuple[str, int]] = {}
    sent_pref: set[str] = set()
    arms: dict[str, tuple[str, int]] = {}
    arm_pref: set[str] = set()
    asok: dict[str, tuple[str, int]] = {}
    series: dict[str, tuple[str, int]] = {}

    for mod in mods:
        for name, line, cond in _health_raises(mod.tree):
            raises.append((name, mod.rel, line, cond))
        for node in mod.walk():
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn in _DECL_METHODS and isinstance(node.func,
                                                      ast.Attribute):
                    lit, pref = _first_arg(node)
                    if lit is not None:
                        ctr_decl.setdefault(lit, (mod.rel, node.lineno))
                        if cn == "add_time_histogram":
                            hist_decl.setdefault(lit, (mod.rel, node.lineno))
                    elif pref is not None:
                        ctr_decl_pref.add(pref)
                elif cn in _MUT_METHODS \
                        and _receiver_last(node) in _PERF_RECEIVERS:
                    lit, pref = _first_arg(node)
                    if lit is not None:
                        ctr_mut.setdefault(lit, (mod.rel, node.lineno))
                    elif pref is not None:
                        ctr_mut_pref.add(pref)
                elif cn == "tracepoint" and len(node.args) >= 2:
                    a, b = node.args[0], node.args[1]
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str) \
                            and isinstance(b, ast.Constant) \
                            and isinstance(b.value, str):
                        tp_sites.setdefault(f"{a.value}.{b.value}",
                                            (mod.rel, node.lineno))
                elif cn == "register_command":
                    lit, _p = _first_arg(node)
                    if lit is not None:
                        asok.setdefault(lit, (mod.rel, node.lineno))
                elif cn == "startswith" \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "prefix":
                    lit, _p = _first_arg(node)
                    if lit is not None:
                        arm_pref.add(lit)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if not (isinstance(k, ast.Constant)
                            and k.value == "prefix"):
                        continue
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        sent.setdefault(v.value, (mod.rel, node.lineno))
                    elif isinstance(v, ast.JoinedStr) and v.values:
                        v0 = v.values[0]
                        if isinstance(v0, ast.Constant) \
                                and isinstance(v0.value, str) and v0.value:
                            sent_pref.add(v0.value)
            elif isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id == "prefix" and len(node.ops) == 1:
                cmp0 = node.comparators[0]
                if isinstance(node.ops[0], ast.Eq) \
                        and isinstance(cmp0, ast.Constant) \
                        and isinstance(cmp0.value, str):
                    arms.setdefault(cmp0.value, (mod.rel, node.lineno))
                elif isinstance(node.ops[0], ast.In) \
                        and isinstance(cmp0, (ast.Tuple, ast.Set, ast.List)):
                    for e in cmp0.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            arms.setdefault(e.value, (mod.rel, node.lineno))
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _SERIES_RE.fullmatch(node.value) \
                    and node.value != "ceph_daemon" \
                    and not node.value.startswith("ceph_tpu"):
                series.setdefault(node.value, (mod.rel, node.lineno))

    # ---- sources of truth --------------------------------------------
    tracer_inv = (parse_tracer_inventory(cfg.tracer_file)
                  if cfg.tracer_file is not None else {})
    obs_text = (read_doc(cfg.docs_observability)
                if cfg.docs_observability is not None else None)
    trc_text = (read_doc(cfg.docs_tracing)
                if cfg.docs_tracing is not None else None)
    doc_text = (obs_text or "") + "\n" + (trc_text or "")
    obs_rel = (rel_of(cfg, cfg.docs_observability)
               if cfg.docs_observability is not None else "")
    trc_rel = (rel_of(cfg, cfg.docs_tracing)
               if cfg.docs_tracing is not None else "")
    tracer_rel = (rel_of(cfg, cfg.tracer_file)
                  if cfg.tracer_file is not None else "")

    def mentioned_outside(name: str, *own: str) -> bool:
        for rel, lits in sym.string_literals.items():
            if rel not in own and name in lits:
                return True
        return False

    # ---- counters -----------------------------------------------------
    for name, (rel, line) in sorted(ctr_mut.items()):
        if name in ctr_decl \
                or any(name.startswith(p) for p in ctr_decl_pref):
            continue
        findings.append(Finding(
            "CL12", rel, line, f"ctr-undeclared:{name}",
            f"perf counter {name!r} is mutated here but never declared "
            f"on any builder — this path raises KeyError at runtime"))
    for name, (rel, line) in sorted(ctr_decl.items()):
        if name in ctr_mut \
                or any(name.startswith(p) for p in ctr_mut_pref) \
                or any(name.startswith(p) for p in sym.fstring_prefixes) \
                or mentioned_outside(name, rel):
            continue
        findings.append(Finding(
            "CL12", rel, line, f"ctr-unused:{name}",
            f"perf counter {name!r} is declared but nothing mutates or "
            f"mentions it — the exported series can only render zero"))

    # ---- tracepoints --------------------------------------------------
    known_tp, known_tp_line = tracer_inv.get("KNOWN_TRACEPOINTS",
                                             (None, 0))
    if known_tp is not None:
        tp_docs = None
        if trc_text is not None:
            tp_docs = {m.group(1)
                       for line_ in trc_text.splitlines()
                       for m in [_DOC_ROW_RE.match(line_.strip())] if m
                       if "." in m.group(1)}
        for name, (rel, line) in sorted(tp_sites.items()):
            if name not in known_tp:
                findings.append(Finding(
                    "CL12", rel, line, f"tp-unknown:{name}",
                    f"tracepoint {name!r} is not catalogued in "
                    f"KNOWN_TRACEPOINTS (common/tracer.py)"))
        for name in sorted(known_tp):
            if name not in tp_sites:
                findings.append(Finding(
                    "CL12", tracer_rel, known_tp_line, f"tp-orphan:{name}",
                    f"KNOWN_TRACEPOINTS entry {name!r} has no emitting "
                    f"site — the catalogue promises an event that never "
                    f"fires"))
            if tp_docs is not None and name not in tp_docs:
                findings.append(Finding(
                    "CL12", tracer_rel, known_tp_line, f"tp-undoc:{name}",
                    f"tracepoint {name!r} is missing from the "
                    f"docs/tracing.md tracepoint table"))
        if tp_docs is not None:
            for name in sorted(tp_docs):
                if name not in known_tp:
                    findings.append(Finding(
                        "CL12", trc_rel, 1, f"tp-orphan-doc:{name}",
                        f"documented tracepoint {name!r} is not in "
                        f"KNOWN_TRACEPOINTS and nothing emits it"))

    # ---- health checks ------------------------------------------------
    raised_names = {n for n, _r, _l, _c in raises}
    for name, rel, line, cond in sorted(raises):
        if not cond:
            findings.append(Finding(
                "CL12", rel, line, f"health-unconditional:{name}",
                f"health check {name!r} is raised unconditionally — it "
                f"can never clear (raise-and-clear symmetry)"))
    if obs_text is not None:
        doc_health = set(_HEALTH_DOC_RE.findall(obs_text))
        for name, rel, line, _cond in sorted(raises):
            if name not in doc_health:
                findings.append(Finding(
                    "CL12", rel, line, f"health-undoc:{name}",
                    f"health check {name!r} is raised but not documented "
                    f"in docs/observability.md (bold check name)"))
        for name in sorted(doc_health - raised_names):
            findings.append(Finding(
                "CL12", obs_rel, 1, f"health-orphan-doc:{name}",
                f"documented health check {name!r} is never raised"))

    # ---- commands -----------------------------------------------------
    handled = set(arms) | set(asok)
    for name, (rel, line) in sorted(sent.items()):
        if name in handled \
                or any(name.startswith(p) for p in arm_pref):
            continue
        findings.append(Finding(
            "CL12", rel, line, f"cmd-unhandled:{name}",
            f"command {name!r} is sent here but no dispatch arm or "
            f"admin-socket registration handles it — it can only error "
            f"on the wire"))
    for name, (rel, line) in sorted(arms.items()):
        if name in sent \
                or any(name.startswith(p) for p in sent_pref) \
                or mentioned_outside(name, rel):
            continue
        findings.append(Finding(
            "CL12", rel, line, f"cmd-unsent:{name}",
            f"dispatch arm for {name!r} but nothing in the package can "
            f"send it — dead dispatch (grow a CLI word-form or retire "
            f"the arm)"))
    if obs_text is not None:
        for name, (rel, line) in sorted(asok.items()):
            if name in doc_text:
                continue
            findings.append(Finding(
                "CL12", rel, line, f"asok-undoc:{name}",
                f"admin-socket command {name!r} is registered but appears "
                f"in neither observability nor tracing docs"))

    # ---- stages -------------------------------------------------------
    if all(k in tracer_inv for k in _STAGE_TUPLES):
        op_stages, op_line = tracer_inv["OP_STAGES"]
        bg_stages, bg_line = tracer_inv["BG_STAGES"]
        rd_stages, rd_line = tracer_inv["READ_STAGES"]
        fg = op_stages | rd_stages
        for name, (rel, line) in sorted(hist_decl.items()):
            if name.startswith("stage_") and name[6:] not in fg:
                findings.append(Finding(
                    "CL12", rel, line, f"stage-unknown:{name}",
                    f"histogram {name!r} names a stage outside the "
                    f"tracer's OP_STAGES/READ_STAGES taxonomy"))
            elif (name.startswith("recovery_")
                  or name.startswith("scrub_")) and name not in bg_stages:
                findings.append(Finding(
                    "CL12", rel, line, f"stage-unknown:{name}",
                    f"histogram {name!r} names a stage outside the "
                    f"tracer's BG_STAGES taxonomy"))
        for s in sorted(fg):
            if f"stage_{s}" not in hist_decl:
                findings.append(Finding(
                    "CL12", tracer_rel,
                    op_line if s in op_stages else rd_line,
                    f"stage-nohist:{s}",
                    f"stage {s!r} has no stage_* latency histogram"))
        for s in sorted(bg_stages):
            if s not in hist_decl:
                findings.append(Finding(
                    "CL12", tracer_rel, bg_line, f"stage-nohist:{s}",
                    f"background stage {s!r} has no latency histogram"))
        if obs_text is not None or trc_text is not None:
            for s in sorted(fg | bg_stages):
                if s not in doc_text:
                    findings.append(Finding(
                        "CL12", tracer_rel,
                        bg_line if s in bg_stages else op_line,
                        f"stage-undoc:{s}",
                        f"stage {s!r} appears in neither tracing nor "
                        f"observability docs"))

    # ---- exported series ---------------------------------------------
    if obs_text is not None:
        tokens = set(_SERIES_DOC_RE.findall(doc_text))
        exact = {t for t in tokens if not t.endswith(("*", "_"))}
        prefixes = {t.rstrip("*") for t in tokens if t.endswith(("*", "_"))}
        for name, (rel, line) in sorted(series.items()):
            if name in exact \
                    or any(name.startswith(p) for p in prefixes):
                continue
            findings.append(Finding(
                "CL12", rel, line, f"series-undoc:{name}",
                f"exported series {name!r} is not documented in "
                f"docs/observability.md (exact token or family "
                f"wildcard)"))
    return findings
