"""CLI: python -m ceph_tpu.qa.analyzer [paths] [--format=text|json|sarif] ...

Exit-code contract (the same contract tests/test_analyzer.py gates on,
and what pre-commit hooks should branch on):

    0   clean: no active findings, no stale baseline entries
    1   findings (or, outside --diff mode, stale baseline entries —
        paid-down debt whose [[suppress]] block must be deleted)
    2   usage or parse errors (bad flag, unreadable baseline, syntax
        error in a scanned file, git failure under --diff)

``--diff BASE_REF`` narrows the REPORT to files changed since BASE_REF
(``git diff --name-only BASE_REF``); the analysis itself stays
whole-package so cross-file checks (CL1 order graph, CL4-CL6 drift
pairings) keep their global view.  Stale-baseline warnings are
suppressed under --diff — a partial view can't judge them.
"""
from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

from .core import BaselineError, Config, format_baseline, render, run


def _diff_files(base_ref: str, roots: list[str]) -> frozenset[str]:
    """Changed *.py files since base_ref, as scan-root-relative posix
    paths (the same form Finding.path uses)."""
    first = Path(roots[0]).resolve()
    repo_dir = first if first.is_dir() else first.parent
    proc = subprocess.run(
        ["git", "diff", "--name-only", "-z", base_ref, "--"],
        cwd=str(repo_dir), capture_output=True, text=True)
    if proc.returncode != 0:
        raise BaselineError(
            f"git diff {base_ref} failed: {proc.stderr.strip()}")
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        cwd=str(repo_dir), capture_output=True, text=True)
    if top.returncode != 0:
        raise BaselineError(
            f"git rev-parse failed: {top.stderr.strip()}")
    repo_root = Path(top.stdout.strip())
    rels: set[str] = set()
    for name in proc.stdout.split("\0"):
        if not name or not name.endswith(".py"):
            continue
        abs_p = (repo_root / name).resolve()
        for r in roots:
            root = Path(r).resolve()
            base = root if root.is_dir() else root.parent
            try:
                rels.add(abs_p.relative_to(base).as_posix())
            except ValueError:
                continue
    return frozenset(rels)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.qa.analyzer",
        description="cephlint: CL1 lock discipline, CL2 shared-state "
                    "races, CL3 JAX tracing hygiene, CL4 failpoint "
                    "drift, CL5 option drift, CL6 wire-protocol "
                    "conformance, CL7 error paths, CL8 kernel "
                    "shape/dtype dataflow, CL9 device-topology "
                    "discipline, CL10 sharding propagation, CL11 "
                    "seeded determinism/purity, CL12 observability "
                    "drift, CL13 resource lifecycle, CL14 teardown "
                    "ordering",
        epilog="exit status: 0 clean; 1 findings (or stale baseline "
               "entries outside --diff mode); 2 usage/parse errors. "
               "--diff BASE_REF reports only files changed since "
               "BASE_REF while still analyzing the whole package.")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to scan (default: the "
                         "ceph_tpu package this analyzer ships in)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--checks", default=None, metavar="CL1,CL2,...",
                    help="comma-separated subset of checks to run")
    ap.add_argument("--diff", default=None, metavar="BASE_REF",
                    help="report only findings on files changed since "
                         "BASE_REF (for pre-commit; analysis stays "
                         "whole-package)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: auto-discovered "
                         "qa/analyzer/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write the active findings as a pinned baseline "
                         "(edit each reason before committing!)")
    args = ap.parse_args(argv)
    if args.write_baseline and args.diff is not None:
        # the baseline pins the WHOLE package's accepted debt; writing
        # it from a diff-narrowed report would silently drop every
        # out-of-scope entry
        ap.error("--write-baseline cannot be combined with --diff")

    paths = args.paths or [str(Path(__file__).resolve().parents[2])]
    cfg = Config.discover(paths)
    if args.baseline is not None:
        cfg.baseline_file = Path(args.baseline)
    if args.no_baseline:
        cfg.use_baseline = False
    if args.checks:
        checks = tuple(c.strip().upper() for c in args.checks.split(","))
        bad = [c for c in checks if c not in cfg.checks]
        if bad:
            ap.error(f"unknown check(s) {', '.join(bad)}")
        cfg.checks = checks

    try:
        if args.diff is not None:
            cfg.diff_files = _diff_files(args.diff, paths)
        report = run(cfg)
    except BaselineError as e:
        print(f"cephlint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        Path(args.write_baseline).write_text(format_baseline(
            report.findings, reason="FIXME: justify or fix"))
        print(f"cephlint: wrote {len(report.findings)} entries to "
              f"{args.write_baseline}")
        return 0

    sarif_prefix = ""
    if args.format == "sarif":
        # code-scanning resolves URIs against the repo root; rebase the
        # scan-root-relative paths when the root sits below the cwd
        import os

        root = Path(paths[0]).resolve()
        base = root if root.is_dir() else root.parent
        rel = os.path.relpath(base, Path.cwd())
        if rel != "." and not rel.startswith(".."):
            sarif_prefix = rel.replace(os.sep, "/") + "/"
    out = render(report, args.format, sarif_prefix)
    if out:
        print(out)
    # stale baseline entries fail here too — the same contract as the
    # tier-1 gate, which asserts the baseline only ever shrinks
    return 0 if report.clean and not report.stale_baseline else 1


if __name__ == "__main__":
    sys.exit(main())
