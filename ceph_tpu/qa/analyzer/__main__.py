"""CLI: python -m ceph_tpu.qa.analyzer [paths] [--format=text|json] ...

Exit status: 0 clean, 1 findings, 2 usage/parse errors — the same
contract as the tier-1 gate in tests/test_analyzer.py.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import BaselineError, Config, format_baseline, render, run


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.qa.analyzer",
        description="cephlint: CL1 lock discipline, CL2 shared-state "
                    "races, CL3 JAX tracing hygiene, CL4 failpoint "
                    "drift, CL5 option drift")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to scan (default: the "
                         "ceph_tpu package this analyzer ships in)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--checks", default=None, metavar="CL1,CL2,...",
                    help="comma-separated subset of checks to run")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: auto-discovered "
                         "qa/analyzer/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write the active findings as a pinned baseline "
                         "(edit each reason before committing!)")
    args = ap.parse_args(argv)

    paths = args.paths or [str(Path(__file__).resolve().parents[2])]
    cfg = Config.discover(paths)
    if args.baseline is not None:
        cfg.baseline_file = Path(args.baseline)
    if args.no_baseline:
        cfg.use_baseline = False
    if args.checks:
        checks = tuple(c.strip().upper() for c in args.checks.split(","))
        bad = [c for c in checks if c not in cfg.checks]
        if bad:
            ap.error(f"unknown check(s) {', '.join(bad)}")
        cfg.checks = checks

    try:
        report = run(cfg)
    except BaselineError as e:
        print(f"cephlint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        Path(args.write_baseline).write_text(format_baseline(
            report.findings, reason="FIXME: justify or fix"))
        print(f"cephlint: wrote {len(report.findings)} entries to "
              f"{args.write_baseline}")
        return 0

    out = render(report, args.format)
    if out:
        print(out)
    # stale baseline entries fail here too — the same contract as the
    # tier-1 gate, which asserts the baseline only ever shrinks
    return 0 if report.clean and not report.stale_baseline else 1


if __name__ == "__main__":
    sys.exit(main())
