"""CL2 — shared-state read-modify-write races.

The `comp_frames_sent` class of bug (ADVICE.md r1): ``self.counter += 1``
compiles to a read, an add, and a write — two threads interleaving them
lose increments.  For every class whose *family* (the class plus its
mixins/bases) is multi-threaded — it spawns threads or owns locks — any
read-modify-write of a plain ``self.<attr>`` outside a lexical
``with <lock>:`` region is reported:

- augmented assignment: ``self.x += 1``, ``self.x |= mask`` ...
- self-referential assignment: ``self.x = self.x + 1``,
  ``old, self.x = self.x, None`` (swap idiom included: the read and the
  write are still two distinct interpreter steps).

``__init__``/``__new__`` run before the object is shared and are exempt,
and so are methods named ``*_locked`` — the Ceph convention asserting
"caller holds the lock" (paxos ``_begin_round_locked``, elector
``_declare_victory_locked``); lockdep's runtime half still catches a
caller that breaks that contract.  Other methods only ever called with
the lock already held carry a ``# noqa: CL2`` with a one-line
justification, or a baseline entry.
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo
from .symbols import ClassInfo, SymbolTable

_EXEMPT_METHODS = {"__init__", "__new__", "__init_subclass__"}


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    by_key = {(c.module, c.name): c for c in sym.classes.values()}
    for mod in mods:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.ClassDef):
                continue
            cls = by_key.get((mod.modname, stmt.name))
            if cls is None or not sym.family_threaded(cls):
                continue
            for fn in stmt.body:
                if not isinstance(fn, ast.FunctionDef) \
                        or fn.name in _EXEMPT_METHODS \
                        or fn.name.endswith("_locked"):
                    continue
                w = _Walker(mod, cls, fn.name, sym)
                w.visit_body(fn.body)
                findings.extend(w.findings)
    return findings


def _is_self_attr(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _reads_self_attr(expr: ast.expr, attr: str) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == attr \
                and isinstance(node.value, ast.Name) and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


class _Walker:
    def __init__(self, mod: ModuleInfo, cls: ClassInfo, fn_name: str,
                 sym: SymbolTable):
        self.mod = mod
        self.cls = cls
        self.fn = fn_name
        self.sym = sym
        self.lock_depth = 0
        self.findings: list[Finding] = []
        self._locks = sym.family_locks(cls)

    def visit_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            guards = sum(
                1 for item in stmt.items
                if self._is_lock_guard(item.context_expr)
            )
            self.lock_depth += guards
            self.visit_body(stmt.body)
            self.lock_depth -= guards
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs run elsewhere (threads, callbacks)
        if self.lock_depth == 0:
            self._inspect(stmt)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self.visit_stmt(child)
            elif isinstance(child, ast.ExceptHandler):
                self.visit_body(child.body)

    def _is_lock_guard(self, expr: ast.expr) -> bool:
        li = self.sym.resolve_lock(expr, self.cls, self.mod.modname)
        if li is not None:
            return True
        # an unresolved but lock-looking context still guards (e.g. a local
        # alias like ``with lock:`` or ``with q.mutex:``) — CL2 errs quiet
        tail = None
        n = expr
        while isinstance(n, ast.Attribute):
            tail = n.attr
            break
        if isinstance(n, ast.Name):
            tail = n.id
        return bool(tail) and any(s in tail.lower()
                                  for s in ("lock", "cond", "mutex"))

    def _inspect(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.AugAssign):
            attr = _is_self_attr(stmt.target)
            if attr and attr not in self._locks:
                self._report(stmt, attr, "augmented assignment")
        elif isinstance(stmt, ast.Assign):
            targets: list[ast.expr] = []
            for t in stmt.targets:
                targets.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            for t in targets:
                attr = _is_self_attr(t)
                if attr and attr not in self._locks \
                        and _reads_self_attr(stmt.value, attr):
                    self._report(stmt, attr, "read-modify-write")

    def _report(self, stmt: ast.stmt, attr: str, what: str) -> None:
        self.findings.append(Finding(
            "CL2", self.mod.rel, stmt.lineno,
            f"{self.cls.name}.{self.fn}:{attr}",
            f"unlocked {what} of self.{attr} in multi-threaded class "
            f"{self.cls.name} (lost-update race); guard with a family lock "
            f"or justify with # noqa: CL2"))
