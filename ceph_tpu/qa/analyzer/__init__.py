"""cephlint — whole-package static analyzer for the framework's four
chronic hygiene hazards (reference: Ceph wires lockdep + clang-analyzer/
cppcheck into make check; this is the AST-level equivalent for the
Python port):

    CL1  lock discipline (order inversions, blocking under a lock,
         lockdep-invisible raw locks)
    CL2  unlocked read-modify-writes on shared state
    CL3  JAX tracing hygiene in ops/, crush/, parallel/, bench/
    CL4  failpoint site / catalogue / docs drift
    CL5  config-option read / declaration drift
    CL6  wire-protocol conformance (encode/decode pairing, field loss,
         MSG_TYPE collisions, dispatch reachability)
    CL7  error paths (swallowed exceptions, unbounded blocking waits,
         reset callbacks mutating shared state without the lock)
    CL8  kernel shape/dtype dataflow in ops/, gf/, crush/

Run it::

    python -m ceph_tpu.qa.analyzer ceph_tpu/ [--format=text|json|sarif]
    cephlint --diff origin/main          # pre-commit: changed files only

Suppress a single finding with ``# noqa: CL#`` on its line; pin a
by-design finding in qa/analyzer/baseline.toml with a mandatory reason.
docs/static_analysis.md is the operator guide; tests/test_analyzer.py
is the tier-1 gate that keeps the package clean.
"""
from .core import (BaselineError, Config, Finding, Report, collect_modules,
                   format_baseline, parse_baseline, render, run)

__all__ = [
    "BaselineError", "Config", "Finding", "Report", "collect_modules",
    "format_baseline", "parse_baseline", "render", "run",
]
