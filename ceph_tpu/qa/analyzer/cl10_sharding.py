"""CL10 — sharding propagation (cephtopo's dataflow half).

CL8 walks kernel bodies with a (shape, dtype) abstract interpreter;
CL10 extends the same style of walk with a PLACEMENT lattice, because
the bugs the multi-chip plane grows are not shape bugs — they are
silent cross-device movement:

    R             replicated (or host-resident) — safe everywhere
    P(dim, axis)  partitioned: array dim `dim` split along mesh axis
                  `axis` (the NamedSharding/PartitionSpec literal form)
    U             unknown — joins to U, never reported

Seeds (within one function body):

- ``PartitionSpec``/``P`` literals: ``P(None, "len")`` / ``P(None,
  LEN_AXIS)`` — the position of the first non-None entry is the
  partitioned dim, its string/name the mesh axis.
- ``NamedSharding(mesh, <spec>)`` bound to a name.
- ``jax.device_put(x, <spec>)`` and ``with_sharding_constraint(x,
  <spec>)`` stamp the value.
- ``jax.jit(f, in_shardings=..., out_shardings=...,
  donate_argnums=...)`` bound to a name: calls through that name
  return the out spec and check donation (below).

Propagation: elementwise binops join (P ⊔ R = P; P ⊔ P with equal
(dim, axis) = P); ``@``/``jnp.dot``/``jnp.matmul``/``dot_general``
track the surviving dims of a 2-D contraction; ``reshape`` forgets to
U (a static walk cannot prove the partitioned dim survives);
``concatenate`` joins its elements; ``x.at[i].set(v)`` (scatter)
propagates ``x`` and joins ``v``; ``all_gather`` replicates.
Function parameters start U, so un-sharded code stays silent.

Finding kinds (ident ``<fn>:<kind>``):

- ``reshard`` — elementwise/concat/scatter operands with provably
  different placements: XLA inserts an implicit all-to-all or gather
  where the code reads as local math.  Reshard deliberately
  (with_sharding_constraint) or fix the spec.
- ``contract-shard`` — a 2-D contraction over a partitioned dim
  (``A @ B`` with A partitioned on its last or B on its first dim):
  the matmul hides an all-gather/psum on the hot path.
- ``sharded-host-trip`` — ``np.*`` / ``jax.device_get`` /
  ``float()``-class coercion / ``.item()``/``.tolist()`` applied to a
  value the lattice proves partitioned: the host copy gathers every
  device's shard through one host thread.
- ``donate-mismatch`` — a donated argument whose placement provably
  differs from the jit's ``out_shardings``: XLA cannot alias the
  buffer, so the donation silently degrades to a copy (and the caller
  has still lost the buffer).

Scope: ``cfg.cl10_dirs`` (default parallel/, ops/) — where sharding
literals live.  Everything un-proven is U and silent; like CL8, this
check prefers missed findings over false alarms.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Config, Finding, ModuleInfo
from .symbols import SymbolTable, attr_chain, call_name

_NUMPY_RECEIVERS = {"np", "numpy", "onp"}
_COERCERS = {"bool", "int", "float", "complex"}
_ITEM_METHODS = {"item", "tolist"}
_SPEC_NAMES = {"P", "PartitionSpec"}


@dataclass(frozen=True)
class SV:
    """One placement lattice element."""

    kind: str        # "rep" | "part" | "unk"
    dim: int = -1
    axis: str = ""

    @property
    def part(self) -> bool:
        return self.kind == "part"


REP = SV("rep")
UNK = SV("unk")


def part(dim: int, axis: str) -> SV:
    return SV("part", dim, axis)


def join(a: SV, b: SV) -> tuple[SV, bool]:
    """(joined, mismatch): mismatch=True when both sides are partitioned
    with different (dim, axis) — the implicit-reshard shape."""
    if a.kind == "unk" or b.kind == "unk":
        return UNK, False
    if a.kind == "rep":
        return b, False
    if b.kind == "rep":
        return a, False
    if (a.dim, a.axis) == (b.dim, b.axis):
        return a, False
    return UNK, True


@dataclass(frozen=True)
class JitWrapper:
    """A name bound to jax.jit(f, ...) with sharding-relevant kwargs."""

    donate: tuple[int, ...]
    out: SV | None   # out_shardings spec when statically known


def check(mods: list[ModuleInfo], sym: SymbolTable, cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    dirs = set(cfg.cl10_dirs)
    for mod in mods:
        if mod.topdir() not in dirs:
            continue
        for node in mod.walk():
            if isinstance(node, ast.FunctionDef):
                interp = _Interp(mod, node)
                interp.run()
                findings.extend(interp.findings)
    return findings


class _Interp:
    def __init__(self, mod: ModuleInfo, fn: ast.FunctionDef):
        self.mod = mod
        self.fn = fn
        self.env: dict[str, SV] = {}
        self.specs: dict[str, SV] = {}      # names bound to sharding specs
        self.jits: dict[str, JitWrapper] = {}
        self.findings: list[Finding] = []
        self._seen: set[str] = set()

    def run(self) -> None:
        for stmt in self.fn.body:
            self._stmt(stmt)

    # -- statements --------------------------------------------------------
    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.FunctionDef):
            return  # nested defs are walked as their own function
        if isinstance(stmt, ast.Assign):
            spec = self._spec_of(stmt.value)
            jitw = self._jit_of(stmt.value)
            val = self._ev(stmt.value)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    if spec is not None:
                        self.specs[t.id] = spec
                    if jitw is not None:
                        self.jits[t.id] = jitw
                    self.env[t.id] = val
            return
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None and isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = self._ev(stmt.value)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._ev(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._ev(stmt.value)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._ev(child)

    # -- sharding-spec literals --------------------------------------------
    def _spec_of(self, expr: ast.expr) -> SV | None:
        """The placement a sharding EXPRESSION denotes, or None when the
        expression isn't (or doesn't resolve to) a spec."""
        if isinstance(expr, ast.Name):
            return self.specs.get(expr.id)
        if not isinstance(expr, ast.Call):
            return None
        cn = call_name(expr)
        if cn in _SPEC_NAMES:
            for i, a in enumerate(expr.args):
                if isinstance(a, ast.Constant) and a.value is None:
                    continue
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    return part(i, a.value)
                if isinstance(a, ast.Name):
                    return part(i, a.id)
                return None  # tuple axes etc.: out of the lattice
            return REP  # P() / P(None, ...): fully replicated
        if cn == "NamedSharding" and len(expr.args) >= 2:
            return self._spec_of(expr.args[1])
        return None

    def _jit_of(self, expr: ast.expr) -> JitWrapper | None:
        if not isinstance(expr, ast.Call):
            return None
        f = expr.func
        is_jit = (isinstance(f, ast.Name) and f.id == "jit") or (
            isinstance(f, ast.Attribute) and f.attr == "jit")
        if not is_jit:
            return None
        donate: tuple[int, ...] = ()
        out: SV | None = None
        for kw in expr.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
                nums = []
                for e in elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        nums.append(e.value)
                donate = tuple(nums)
            elif kw.arg == "out_shardings":
                out = self._spec_of(kw.value)
        if not donate and out is None:
            return None
        return JitWrapper(donate=donate, out=out)

    # -- expressions -------------------------------------------------------
    def _ev(self, expr: ast.expr) -> SV:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, UNK)
        if isinstance(expr, ast.Constant):
            return REP
        if isinstance(expr, (ast.Tuple, ast.List)):
            sv = REP
            for e in expr.elts:
                sv, mism = join(sv, self._ev(e))
                if mism:
                    self._report(expr, "reshard",
                                 "sequence mixes differently-partitioned "
                                 "values — downstream ops reshard")
            return sv
        if isinstance(expr, ast.BinOp):
            lv, rv = self._ev(expr.left), self._ev(expr.right)
            if isinstance(expr.op, ast.MatMult):
                return self._contract(expr, lv, rv)
            sv, mism = join(lv, rv)
            if mism:
                self._report(expr, "reshard",
                             "elementwise op on operands with different "
                             "placements — XLA inserts an implicit "
                             "reshard here")
            return sv
        if isinstance(expr, ast.UnaryOp):
            return self._ev(expr.operand)
        if isinstance(expr, ast.IfExp):
            sv, _ = join(self._ev(expr.body), self._ev(expr.orelse))
            return sv
        if isinstance(expr, ast.Subscript):
            base = self._ev(expr.value)
            return base if base.part else UNK
        if isinstance(expr, ast.Call):
            return self._call(expr)
        if isinstance(expr, ast.Attribute):
            return self._ev(expr.value) if expr.attr in ("T", "real",
                                                         "imag") else UNK
        return UNK

    def _call(self, node: ast.Call) -> SV:
        cn = call_name(node)
        f = node.func
        args = node.args

        # seeds -------------------------------------------------------
        # the placement rides the RETURN value; the host-side input
        # name keeps its old lattice element (device_put copies)
        if cn == "device_put" and args:
            if len(args) >= 2:
                spec = self._spec_of(args[1])
                if spec is not None:
                    return spec
            return UNK
        if cn == "with_sharding_constraint" and len(args) >= 2:
            spec = self._spec_of(args[1])
            return spec if spec is not None else UNK

        # collectives / shape ops ------------------------------------
        if cn == "all_gather":
            for a in args:
                self._ev(a)
            return REP
        if cn == "reshape":
            for a in args:
                self._ev(a)
            return UNK
        if cn in ("concatenate", "stack", "hstack", "vstack"):
            sv = REP
            elems = args[0].elts if args and isinstance(
                args[0], (ast.Tuple, ast.List)) else args
            for e in elems:
                sv, mism = join(sv, self._ev(e))
                if mism:
                    self._report(node, "reshard",
                                 f"jnp.{cn} over differently-partitioned "
                                 f"operands — implicit reshard")
            return sv
        if cn in ("dot", "matmul", "dot_general", "tensordot") \
                and len(args) >= 2:
            return self._contract(node, self._ev(args[0]),
                                  self._ev(args[1]))
        if cn == "set" and isinstance(f, ast.Attribute):
            # x.at[i].set(v): scatter — propagate x, join the update
            base = f.value
            if isinstance(base, ast.Subscript) \
                    and isinstance(base.value, ast.Attribute) \
                    and base.value.attr == "at":
                xv = self._ev(base.value.value)
                uv = self._ev(args[0]) if args else REP
                sv, mism = join(xv, uv)
                if mism:
                    self._report(node, "reshard",
                                 "scatter update placed differently from "
                                 "its target — implicit reshard")
                return sv

        # host trips --------------------------------------------------
        if isinstance(f, ast.Attribute):
            ch = attr_chain(f)
            root = ch[0] if ch else None
            if root in _NUMPY_RECEIVERS and any(
                    self._ev(a).part for a in args):
                self._report(node, "sharded-host-trip",
                             f"host numpy call {root}.{f.attr}(...) on a "
                             f"partitioned value — gathers every shard "
                             f"through the host")
                return REP
            if f.attr == "device_get" and args and self._ev(args[0]).part:
                self._report(node, "sharded-host-trip",
                             "jax.device_get on a partitioned value — "
                             "cross-device gather hidden in a host copy")
                return REP
            if f.attr in _ITEM_METHODS and self._ev(f.value).part:
                self._report(node, "sharded-host-trip",
                             f".{f.attr}() on a partitioned value — "
                             f"host sync + gather")
                return REP
        if isinstance(f, ast.Name) and f.id in _COERCERS and args \
                and self._ev(args[0]).part:
            self._report(node, "sharded-host-trip",
                         f"{f.id}() on a partitioned value — host sync "
                         f"+ gather")
            return REP

        # calls through a recorded jit wrapper ------------------------
        if isinstance(f, ast.Name) and f.id in self.jits:
            w = self.jits[f.id]
            for i in w.donate:
                if i < len(args):
                    av = self._ev(args[i])
                    if av.part and w.out is not None and w.out != av:
                        self._report(
                            node, "donate-mismatch",
                            f"donated arg {i} is partitioned "
                            f"({av.axis}@dim{av.dim}) but out_shardings "
                            f"differs — XLA cannot alias the buffer, the "
                            f"donation degrades to a copy")
            for a in args:
                self._ev(a)
            return w.out if w.out is not None else UNK

        # anything else: evaluate args for side findings, answer U
        for a in args:
            self._ev(a)
        for kw in node.keywords:
            self._ev(kw.value)
        return UNK

    def _contract(self, node: ast.AST, lv: SV, rv: SV) -> SV:
        """2-D contraction: A [m, k] @ B [k, n] -> [m, n].  A partitioned
        contracting dim (A dim1 / B dim0) hides a gather/psum."""
        if (lv.part and lv.dim == 1) or (rv.part and rv.dim == 0):
            self._report(node, "contract-shard",
                         "contraction over a partitioned dim — the "
                         "matmul hides an all-gather/psum; reshard the "
                         "operand or shard the batch dim instead")
            return UNK
        if lv.part and lv.dim == 0:
            return lv
        if rv.part and rv.dim == 1:
            return rv
        if lv.kind == "rep" and rv.kind == "rep":
            return REP
        return UNK

    def _report(self, node: ast.AST, kind: str, msg: str) -> None:
        ident = f"{self.fn.name}:{kind}"
        n = 2
        while ident in self._seen:
            ident = f"{self.fn.name}:{kind}:{n}"
            n += 1
        self._seen.add(ident)
        self.findings.append(Finding(
            "CL10", self.mod.rel, getattr(node, "lineno", self.fn.lineno),
            ident, msg))
