"""CL11 — seeded determinism / purity discipline.

Replay is the load-bearing contract of the qa plane: thrasher and
StormPlanner ``plan()`` re-run with the same seed and assert
event-for-event equality, the mgr controllers are pure ``plan()``
loops over observed series, and the traffic generators draw from
``derive_rng`` named streams.  All of that holds only while nothing on
the plan path reads ambient state.  CL11 makes the contract static
over ``cfg.cl11_plan_dirs``:

- ``ambient-rng:<func>:<what>`` — module-global RNG anywhere in a plan
  module: ``random.<draw>()`` / ``np.random.<draw>`` global state, or
  ``random.Random()`` / ``default_rng()`` constructed with NO seed
  argument.  Seeded constructions (``random.Random(self.seed)``,
  ``derive_rng(seed, "tenant", i)``) pass.
- ``ambient-clock:<func>:<what>`` — a ``time.time()`` / datetime-now
  wall-clock read anywhere in a plan module (deadline loops in
  execution harnesses are the deliberate, baselined exceptions).
- ``wall-clock:<func>:<what>`` — ANY clock read (wall or monotonic,
  including the tracer's ``trace_now``) inside a function reachable
  from a ``cfg.cl11_pure_roots`` entry.  Injected clocks are exempt by
  construction: a ``clock()`` parameter call never matches the ambient
  patterns.
- ``unordered-iter:<func>:<name>`` — iteration over a locally-built
  set (or ``.keys()/.values()/.items()`` of one) without ``sorted()``
  inside a reachable function; set order is hash-seed-dependent, so an
  event emitted from it breaks the plan digest across processes.
- ``impure:<func>:<target>`` — ``self.<attr>`` assignment/deletion or
  a ``global`` statement inside a declared-pure root.  Deliberate
  fold-state writes (the planner's replay artifact, the progress
  tracker's event table) carry noqa/baseline entries saying so.

Function identity is ``Class.method`` or the bare module-level name;
idents carry no line numbers so baseline entries survive edits.
"""
from __future__ import annotations

import ast

from .core import Config, Finding, ModuleInfo
from .symbols import attr_chain, call_name

#: module-level random draws that read the shared global RNG state
_RAND_DRAWS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "betavariate", "expovariate",
    "triangular", "normalvariate", "lognormvariate", "vonmisesvariate",
    "paretovariate", "weibullvariate", "getrandbits", "randbytes",
    "seed",
}
#: wall-clock reads (break replay identity outright)
_WALL = {("time", ("time",)), ("time", ("time_ns",)),
         ("datetime", ("now",)), ("datetime", ("utcnow",)),
         ("datetime", ("datetime", "now")),
         ("datetime", ("datetime", "utcnow"))}
#: additional process-clock reads that are still nondeterministic on
#: the PURE call graph (fine in execution/measurement code)
_MONO = {("time", ("monotonic",)), ("time", ("monotonic_ns",)),
         ("time", ("perf_counter",)), ("time", ("perf_counter_ns",))}


def _in_plan_dirs(rel: str, cfg: Config) -> bool:
    for d in cfg.cl11_plan_dirs:
        d = d.rstrip("/")
        if rel == d or rel.startswith(d + "/"):
            return True
    return False


def _functions(mod: ModuleInfo):
    """(qual, class_name | None, node) for every module-level function
    and every method of a module-level class."""
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt.name, None, stmt
        elif isinstance(stmt, ast.ClassDef):
            for s in stmt.body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{stmt.name}.{s.name}", stmt.name, s


def _rng_violation(node: ast.Call) -> str | None:
    """Name of the ambient-RNG pattern this call matches, or None."""
    ch = attr_chain(node.func)
    if ch is not None:
        base, attrs = ch
        if base == "random" and len(attrs) == 1:
            if attrs[0] in _RAND_DRAWS:
                return f"random.{attrs[0]}"
            if attrs[0] == "Random" and not node.args:
                return "random.Random()"
        if base in ("np", "numpy") and attrs[:1] == ["random"]:
            if len(attrs) == 2 and attrs[1] == "default_rng":
                if not node.args:
                    return f"{base}.random.default_rng()"
            elif len(attrs) == 2:
                return f"{base}.random.{attrs[1]}"
    cn = call_name(node)
    if cn == "default_rng" and isinstance(node.func, ast.Name) \
            and not node.args:
        return "default_rng()"
    if cn == "Random" and isinstance(node.func, ast.Name) \
            and not node.args:
        return "Random()"
    return None


def _clock_violation(node: ast.Call, monotonic: bool) -> str | None:
    ch = attr_chain(node.func)
    if ch is not None:
        key = (ch[0], tuple(ch[1]))
        if key in _WALL:
            return ".".join((ch[0],) + tuple(ch[1]))
        if monotonic and key in _MONO:
            return ".".join((ch[0],) + tuple(ch[1]))
    if monotonic and isinstance(node.func, ast.Name) \
            and node.func.id == "trace_now":
        # the tracer's shared clock funnel is time.time by contract
        return "trace_now"
    return None


def _set_locals(fn: ast.AST) -> set[str]:
    """Names assigned a provably-unordered value (set literal/ctor/
    comprehension) anywhere in the function body."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            v = node.value
            unordered = isinstance(v, (ast.Set, ast.SetComp)) or (
                isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id in ("set", "frozenset"))
            if unordered:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            v = node.value
            if isinstance(v, (ast.Set, ast.SetComp)) or (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("set", "frozenset")):
                if isinstance(node.target, ast.Name):
                    out.add(node.target.id)
    return out


def _unordered_iters(fn: ast.AST):
    """(name, line) for every for-loop / comprehension iterating a
    locally-built set (directly or via .keys/.values/.items) without an
    ordering wrapper."""
    tracked = _set_locals(fn)
    iters: list[tuple[ast.expr, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append((node.iter, node.lineno))
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                iters.append((gen.iter, node.lineno))
    for expr, line in iters:
        if isinstance(expr, ast.Name) and expr.id in tracked:
            yield expr.id, line
        elif isinstance(expr, (ast.Set, ast.SetComp)):
            yield "<set-literal>", line
        elif isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in ("set", "frozenset"):
                yield expr.func.id + "()", line
            elif isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in ("keys", "values", "items") \
                    and isinstance(expr.func.value, ast.Name) \
                    and expr.func.value.id in tracked:
                yield f"{expr.func.value.id}.{expr.func.attr}()", line


def _self_mutations(fn: ast.AST):
    """(attr, line) for self.<attr> writes/deletes and ('global-<n>',
    line) for global statements."""
    def self_attr(t: ast.expr) -> str | None:
        # self.x / self.x[...] / self.x.y — first attribute off self
        while isinstance(t, ast.Subscript):
            t = t.value
        ch = attr_chain(t)
        if ch is not None and ch[0] == "self" and ch[1]:
            return ch[1][0]
        return None

    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                a = self_attr(t)
                if a is not None:
                    yield a, node.lineno
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = self_attr(t)
                if a is not None:
                    yield a, node.lineno
        elif isinstance(node, ast.Global):
            for n in node.names:
                yield f"global-{n}", node.lineno


def check(mods: list[ModuleInfo], sym, cfg: Config) -> list[Finding]:
    plan_mods = [m for m in mods if _in_plan_dirs(m.rel, cfg)]
    if not plan_mods:
        return []

    # function inventory + call-graph edges over the plan modules
    funcs: dict[str, tuple[ModuleInfo, str | None, ast.AST]] = {}
    by_bare: dict[str, list[str]] = {}
    for mod in plan_mods:
        for qual, clsname, node in _functions(mod):
            key = f"{mod.rel}::{qual}"
            funcs[key] = (mod, clsname, node)
            by_bare.setdefault(qual.rsplit(".", 1)[-1], []).append(key)

    roots = [k for k, (_m, _c, _n) in funcs.items()
             if k.split("::", 1)[1] in cfg.cl11_pure_roots
             or k.split("::", 1)[1].rsplit(".", 1)[-1]
             in cfg.cl11_pure_roots and "." not in k.split("::", 1)[1]]

    # BFS: self.<m>() -> same-class method, bare f() -> module-level
    # function anywhere in the plan modules (by unique name)
    reachable: set[str] = set()
    work = list(roots)
    while work:
        key = work.pop()
        if key in reachable:
            continue
        reachable.add(key)
        mod, clsname, node = funcs[key]
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            nxt: str | None = None
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self" and clsname is not None:
                cand = f"{mod.rel}::{clsname}.{f.attr}"
                if cand in funcs:
                    nxt = cand
            elif isinstance(f, ast.Name):
                cands = [c for c in by_bare.get(f.id, ())
                         if "." not in c.split("::", 1)[1]]
                if len(cands) == 1:
                    nxt = cands[0]
            if nxt is not None and nxt not in reachable:
                work.append(nxt)

    findings: list[Finding] = []
    seen: set[tuple[str, str, str]] = set()

    def emit(mod: ModuleInfo, line: int, ident: str, msg: str) -> None:
        k = ("CL11", mod.rel, ident)
        if k not in seen:
            seen.add(k)
            findings.append(Finding("CL11", mod.rel, line, ident, msg))

    for key, (mod, clsname, node) in sorted(funcs.items()):
        qual = key.split("::", 1)[1]
        on_graph = key in reachable
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                what = _rng_violation(sub)
                if what is not None:
                    emit(mod, sub.lineno, f"ambient-rng:{qual}:{what}",
                         f"{what} in {qual}() reads ambient RNG state — "
                         f"derive a seeded stream (derive_rng / "
                         f"random.Random(seed)) instead")
                    continue
                clock = _clock_violation(sub, monotonic=on_graph)
                if clock is not None:
                    if on_graph:
                        emit(mod, sub.lineno, f"wall-clock:{qual}:{clock}",
                             f"{clock}() inside {qual}(), which is on "
                             f"the pure-plan call graph — take the "
                             f"timestamp as a parameter / injected "
                             f"clock so replay stays bit-exact")
                    else:
                        emit(mod, sub.lineno,
                             f"ambient-clock:{qual}:{clock}",
                             f"{clock}() wall-clock read in plan module "
                             f"function {qual}() — use an injected "
                             f"clock or time.monotonic for deadlines "
                             f"(baseline deliberate sites)")
        if on_graph:
            for name, line in _unordered_iters(node):
                emit(mod, line, f"unordered-iter:{qual}:{name}",
                     f"iteration over unordered {name} in {qual}() on "
                     f"the plan path — wrap in sorted() so emission "
                     f"order is deterministic")
        if key in roots:
            for attr, line in _self_mutations(node):
                emit(mod, line, f"impure:{qual}:{attr}",
                     f"{qual}() is declared pure (cl11_pure_roots) but "
                     f"mutates {attr!r} — return the value, or noqa/"
                     f"baseline the deliberate fold-state write")

    # module-level statements of plan modules (import-time draws or
    # clock reads are ambient by definition)
    for mod in plan_mods:
        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                what = _rng_violation(sub)
                if what is not None:
                    emit(mod, sub.lineno, f"ambient-rng:<module>:{what}",
                         f"{what} at module scope reads ambient RNG "
                         f"state — seed it explicitly")
                    continue
                clock = _clock_violation(sub, monotonic=False)
                if clock is not None:
                    emit(mod, sub.lineno, f"ambient-clock:<module>:{clock}",
                         f"{clock}() wall-clock read at module scope "
                         f"of a plan module")
    return findings
