"""cephheal CI smoke: recovery-plane observability end to end
(qa/ci_gate.sh step 9; ISSUE 13 acceptance).

Drives the WHOLE surface through the production path, no shortcuts:

1. a LocalCluster (mgr hosted, k+m OSDs so a kill leaves a hole CRUSH
   cannot remap around) with ``trace_sampling_rate=0`` and tail
   sampling armed; two named clients write continuously;
2. one OSD is killed mid-traffic: ``PG_DEGRADED`` must raise with
   per-PG degraded counts, and the progress module must open recovery
   events;
3. the OSD is revived: degraded objects must drain to 0, every event
   must complete at fraction 1.0, and the health checks must clear;
4. the ``ceph_recovery_*{pool,codec}`` labeled series must render on
   the prometheus exporter with a plausible repair ratio
   (bytes_read/bytes_repaired ~ k for the RS pool, within tolerance);
5. the tail-promoted recovery trace must assemble into a connected
   cross-entity tree (recovery root reaching a replica_commit or
   recovery_push on another daemon) — at sampling=0, so promotion did
   the work.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it next to the SARIF artifacts).
"""
from __future__ import annotations

import contextlib
import json
import sys
import threading
import time

K, M = 2, 1
WSIZE = 4096
POOL = "healsmoke"


from .smoke_util import (assert_no_leaked_threads, scrape as _scrape,
                         wait_for as _wait)


def _series(body: str, metric: str) -> dict[str, float]:
    """{label-block: value} of one metric's samples."""
    out = {}
    for line in body.splitlines():
        if line.startswith(metric + "{"):
            labels, _, val = line.partition("} ")
            out[labels[len(metric) + 1:]] = float(val)
    return out


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..common.tracer import TRACER, connected_traces
    from ..qa.vstart import LocalCluster

    problems: list[str] = []
    summary: dict = {}
    TRACER.enable(False)
    TRACER.clear()
    overrides = {
        "mgr_report_interval": 0.2,
        "mgr_digest_interval": 0.2,
        "mgr_progress_interval": 0.2,
        "mgr_recovery_stalled_grace": 1.5,
        "mgr_stale_report_age": 30.0,
        "trace_enabled": True,
        "trace_sampling_rate": 0.0,   # head sampling OFF: tail must win
        "trace_tail_latency_ms": 40.0,
    }
    # Runtime twin of the CL13/CL14 lints: every thread bring-up starts
    # must be gone after teardown.  Held open across the whole cluster
    # lifecycle; closed below so a leak lands in `problems` (the JSON
    # summary still renders) instead of a bare traceback.
    leak_gate = contextlib.ExitStack()
    leak_gate.enter_context(assert_no_leaked_threads())
    with LocalCluster(n_mons=1, n_osds=K + M, with_mgr=True,
                      conf_overrides=overrides) as c:
        c.create_ec_pool(POOL, k=K, m=M, pg_num=4)
        stop = threading.Event()
        wrote: dict[str, int] = {"client.alpha": 0, "client.beta": 0}
        errors: list[str] = []

        def writer(name: str) -> None:
            io = c.client(name).open_ioctx(POOL)
            i = 0
            while not stop.is_set():
                try:
                    io.write_full(f"{name}-{i}", bytes([i % 251 + 1])
                                  * WSIZE)
                    wrote[name] += 1
                except Exception as e:
                    # a write refused mid-kill is the scenario working;
                    # record only so a TOTAL failure is diagnosable
                    errors.append(f"{name}: {e!r}")
                    time.sleep(0.2)
                i += 1
                time.sleep(0.05)

        threads = [threading.Thread(target=writer, args=(n,), daemon=True)
                   for n in wrote]
        for t in threads:
            t.start()
        time.sleep(1.0)  # baseline traffic

        victim = K + M - 1
        c.kill_osd(victim)
        rv, _ = c.mon_command({"prefix": "osd down", "id": victim})
        if rv != 0:
            problems.append(f"osd down refused: {rv}")

        observed = {"degraded": False, "events": False}

        def degraded_visible() -> bool:
            rv2, st = c.mon_command({"prefix": "status"})
            if rv2 != 0:
                return False
            checks = (st.get("health") or {}).get("checks") or {}
            observed["degraded"] |= "PG_DEGRADED" in checks
            observed["events"] |= bool(
                (st.get("progress") or {}).get("events"))
            return observed["degraded"] and observed["events"]

        if not _wait(degraded_visible, timeout=15.0):
            problems.append(
                f"degraded surface incomplete while OSD down: {observed}")

        c.revive_osd(victim)
        c.mon_command({"prefix": "osd in", "id": victim})

        def healed() -> bool:
            rv2, st = c.mon_command({"prefix": "status"})
            if rv2 != 0:
                return False
            checks = (st.get("health") or {}).get("checks") or {}
            if set(checks) & {"PG_DEGRADED", "RECOVERY_STALLED",
                              "OSD_DOWN"}:
                return False
            pg_info = st.get("pgs_by_state") or {}
            return bool(pg_info)

        healed_ok = _wait(healed, timeout=40.0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        if not healed_ok:
            problems.append("degraded objects never drained to 0 "
                            "(health checks stuck)")

        # -- progress reached 1.0 -------------------------------------
        rv, prog = c.mon_command({"prefix": "progress"})
        if rv != 0:
            problems.append(f"`progress` failed: {rv} {prog}")
        else:
            done = prog.get("completed") or []
            summary["completed_events"] = len(done)
            if not done:
                problems.append("no completed recovery progress events")
            elif any(e.get("progress") != 1.0 for e in done):
                problems.append(f"completed event below 1.0: {done}")
            if prog.get("events"):
                problems.append(
                    f"events still in flight after heal: {prog['events']}")

        # -- ceph_recovery_* on the exporter with a plausible ratio ----
        url = c.mgr.module("prometheus").url
        read_s: dict = {}
        rep_s: dict = {}

        def recovery_series() -> bool:
            nonlocal read_s, rep_s
            body = _scrape(url)
            read_s = _series(body, "ceph_recovery_bytes_read")
            rep_s = _series(body, "ceph_recovery_bytes_repaired")
            return bool(read_s) and bool(rep_s)

        if not _wait(recovery_series, timeout=10.0):
            problems.append("ceph_recovery_* series never rendered on "
                            "the prometheus exporter")
        else:
            bytes_read = sum(read_s.values())
            bytes_rep = sum(rep_s.values())
            ratio = bytes_read / bytes_rep if bytes_rep else None
            summary["bytes_read"] = bytes_read
            summary["bytes_repaired"] = bytes_rep
            summary["repair_ratio"] = ratio
            # plan-path RS repairs read exactly k chunks per repaired
            # chunk; occasional full-gather fallbacks under live
            # traffic can nudge it up, never below k
            if ratio is None or not (K * 0.9 <= ratio <= (K + M + 1)):
                problems.append(
                    f"repair ratio {ratio} implausible for RS(k={K}) "
                    f"(want ~{K})")

        # -- tail-promoted connected recovery trace --------------------
        spans = TRACER.spans()
        summary["recovery_spans"] = sum(
            1 for s in spans if s["name"] == "recovery")
        conn = (connected_traces(spans, root="recovery",
                                 leaf="replica_commit")
                or connected_traces(spans, root="recovery",
                                    leaf="recovery_push"))
        if not conn:
            problems.append(
                "no connected recovery trace tree at sampling=0 "
                "(tail promotion failed)")
        else:
            ents = {s["entity"] for s in spans
                    if s["trace_id"] == conn[0]}
            summary["trace_entities"] = sorted(ents)
            if len(ents) < 2:
                problems.append(
                    f"recovery trace is not cross-entity: {sorted(ents)}")

        summary["writes"] = dict(wrote)
        summary["write_errors"] = len(errors)
        if not all(wrote.values()):
            problems.append(f"a client never completed a write: {wrote} "
                            f"(first errors: {errors[:3]})")

    try:
        leak_gate.close()
    except AssertionError as e:
        problems.append(str(e))

    TRACER.enable(False)
    TRACER.clear()
    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
