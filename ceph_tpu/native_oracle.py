"""ctypes bridge to the C++ oracles in native/.

The oracles are the framework's stand-in for the reference's native
jerasure/gf-complete/ISA-L/mapper.c stack (SURVEY.md §7 "native/"): they are
the bit-exactness referees the JAX path is tested against and the CPU
baseline for BASELINE.md.  pybind11 is not in this image, so the bridge is
plain ctypes over a C ABI; the library is built on demand with `make`.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libceph_tpu_oracle.so")

_u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")


class OracleUnavailable(RuntimeError):
    pass


@lru_cache(maxsize=1)
def _lib() -> ctypes.CDLL:
    # keep the generated LN table header in sync with its Python generator
    # (the C++ crush oracle must use byte-identical tables)
    from .crush.ln_table import emit_c_header

    emit_c_header(os.path.join(_NATIVE_DIR, "crush_tables.h"))
    srcs = [
        os.path.join(_NATIVE_DIR, f)
        for f in os.listdir(_NATIVE_DIR)
        if f.endswith((".cc", ".h"))
    ]
    if not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(s) >= os.path.getmtime(_LIB_PATH) for s in srcs
    ):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                text=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise OracleUnavailable(
                f"failed to build native oracle (make -C native): {detail}"
            ) from e
    lib = ctypes.CDLL(_LIB_PATH)

    lib.gfo_mul.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.gfo_mul.restype = ctypes.c_int
    lib.gfo_div.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.gfo_div.restype = ctypes.c_int
    lib.gfo_n_ones.argtypes = [ctypes.c_int]
    lib.gfo_n_ones.restype = ctypes.c_int
    lib.gfo_mul_table.argtypes = [_u8p]
    lib.gfo_mul_table.restype = None
    for name in ("gfo_vandermonde", "gfo_cauchy_original", "gfo_cauchy_good"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_int, ctypes.c_int, _u8p]
        fn.restype = ctypes.c_int
    lib.gfo_invert.argtypes = [_u8p, ctypes.c_int, _u8p]
    lib.gfo_invert.restype = ctypes.c_int
    lib.gfo_apply.argtypes = [_u8p, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_long, _u8p]
    lib.gfo_apply.restype = None
    lib.gfo_apply_fast.argtypes = [_u8p, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_long, _u8p]
    lib.gfo_apply_fast.restype = ctypes.c_int
    lib.gfo_encode.argtypes = [_u8p, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_long, _u8p]
    lib.gfo_encode.restype = None
    lib.gfo_encode_fast.argtypes = [_u8p, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_long, _u8p]
    lib.gfo_encode_fast.restype = ctypes.c_int
    lib.gfo_decode.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, _i32p, ctypes.c_int, _u8p,
        ctypes.c_long, _u8p,
    ]
    lib.gfo_decode.restype = ctypes.c_int
    for name in ("ceph_tpu_crc32c", "ceph_tpu_crc32c_sw"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_uint32, _u8p, ctypes.c_size_t]
        fn.restype = ctypes.c_uint32
    return lib


def available() -> bool:
    try:
        _lib()
        return True
    except OracleUnavailable:
        return False


def gf_mul(a: int, b: int) -> int:
    return _lib().gfo_mul(a, b)


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    return _lib().gfo_div(a, b)


def n_ones(n: int) -> int:
    return _lib().gfo_n_ones(n)


def mul_table() -> np.ndarray:
    out = np.empty((256, 256), dtype=np.uint8)
    _lib().gfo_mul_table(out.reshape(-1))
    return out


def vandermonde(k: int, m: int) -> np.ndarray:
    out = np.empty(m * k, dtype=np.uint8)
    rc = _lib().gfo_vandermonde(k, m, out)
    if rc != 0:
        raise ValueError(f"gfo_vandermonde(k={k}, m={m}) failed rc={rc}")
    return out.reshape(m, k)


def cauchy_original(k: int, m: int) -> np.ndarray:
    out = np.empty(m * k, dtype=np.uint8)
    rc = _lib().gfo_cauchy_original(k, m, out)
    if rc != 0:
        raise ValueError(f"gfo_cauchy_original(k={k}, m={m}) failed rc={rc}")
    return out.reshape(m, k)


def cauchy_good(k: int, m: int) -> np.ndarray:
    out = np.empty(m * k, dtype=np.uint8)
    rc = _lib().gfo_cauchy_good(k, m, out)
    if rc != 0:
        raise ValueError(f"gfo_cauchy_good(k={k}, m={m}) failed rc={rc}")
    return out.reshape(m, k)


def invert(mat: np.ndarray) -> np.ndarray:
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    n = mat.shape[0]
    out = np.empty((n, n), dtype=np.uint8)
    rc = _lib().gfo_invert(mat.reshape(-1), n, out.reshape(-1))
    if rc != 0:
        raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
    return out


def encode(coding: np.ndarray, data: np.ndarray, fast: bool = False) -> np.ndarray:
    """Parity via the oracle; data [k, len] uint8 -> [m, len] uint8."""
    coding = np.ascontiguousarray(coding, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = coding.shape
    assert data.shape[0] == k
    length = data.shape[1]
    parity = np.empty((m, length), dtype=np.uint8)
    fn = _lib().gfo_encode_fast if fast else _lib().gfo_encode
    fn(coding.reshape(-1), k, m, data.reshape(-1), length, parity.reshape(-1))
    return parity


def apply_matrix(mat: np.ndarray, chunks: np.ndarray, fast: bool = True) -> np.ndarray:
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    rows, n = mat.shape
    assert chunks.shape[0] == n
    length = chunks.shape[1]
    out = np.empty((rows, length), dtype=np.uint8)
    fn = _lib().gfo_apply_fast if fast else _lib().gfo_apply
    fn(mat.reshape(-1), rows, n, chunks.reshape(-1), length, out.reshape(-1))
    return out


def crc32c(data, seed: int = 0xFFFFFFFF, _sw: bool = False) -> int:
    """crc32c over bytes-like data, reference convention (no final xor;
    reference: src/common/crc32c.cc :: ceph_crc32c).  _sw forces the
    table-driven path so tests can cross-check the hardware instruction."""
    buf = np.frombuffer(memoryview(data).cast("B"), dtype=np.uint8)
    fn = _lib().ceph_tpu_crc32c_sw if _sw else _lib().ceph_tpu_crc32c
    return int(fn(seed & 0xFFFFFFFF, buf, buf.size))


def decode(
    coding: np.ndarray, k: int, available_rows: list[int], shards: np.ndarray
) -> np.ndarray:
    """Rebuild data chunks [k, len] from >= k shard rows (sorted ids)."""
    coding = np.ascontiguousarray(coding, dtype=np.uint8)
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    m = coding.shape[0]
    rows = np.asarray(available_rows, dtype=np.int32)
    if shards.shape[0] < min(len(rows), k):
        raise ValueError(
            f"shards has {shards.shape[0]} rows, need >= {min(len(rows), k)}"
        )
    length = shards.shape[1]
    out = np.empty((k, length), dtype=np.uint8)
    rc = _lib().gfo_decode(
        coding.reshape(-1), k, m, rows, len(rows), shards.reshape(-1), length,
        out.reshape(-1),
    )
    if rc != 0:
        raise ValueError(f"gfo_decode failed rc={rc}")
    return out
