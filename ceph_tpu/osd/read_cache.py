"""cephread hot-object read cache (reference: the OSD's object context
cache / BlueStore's 2Q onode cache, radically simplified).

A byte-bounded LRU of fully-materialized objects on the PRIMARY,
serving repeat GETs without a chunk gather or decode.  Entries are
keyed by (pgid, oid) and stamped with the object version that produced
them; two mechanisms keep a hit honest:

- **Write-path invalidation**: every mutation that bumps the object
  version (client write, RMW, delete — and, belt-and-braces, a replica
  sub-write apply in case this daemon regains primariness later) calls
  `invalidate()`.
- **Version validation on read**: a hit is served only when the cached
  version equals the PG log's newest version for the oid
  (`pg.log.obj_newest`) — so even a missed invalidation (primary
  flapped away and back while another OSD wrote) degrades to a miss,
  never a stale read.  No log row for the oid → miss.

Promotion is demand-driven by cephmeter: `_ec_read` consults the
per-(client,pool) accounting table and only inserts when the reading
identity has accumulated `osd_read_cache_promote_ops` read ops — a
heavy hitter's working set sticks, a cold one-pass scan never churns
the cache (the classic scan-resistance argument, minus the second
queue).  Only HEALTHY full-object reads fill: a ranged degraded decode
produces a byte window, not an object, and caching reconstructed data
would hide the degradation from scrub.
"""
from __future__ import annotations

from collections import OrderedDict

from ..common.lockdep import make_lock


class ReadCache:
    """Bounded LRU of (pgid, oid) -> (version, object bytes)."""

    def __init__(self, max_bytes: int = 0, logger=None):
        self._logger = logger
        self._lock = make_lock("osd::read_cache")
        self._max_bytes = int(max_bytes)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._inserts = 0
        self._evictions = 0
        self._invalidations = 0

    # -- config ------------------------------------------------------------
    def set_max_bytes(self, max_bytes: int) -> None:
        with self._lock:
            self._max_bytes = int(max_bytes)
            ev = self._evict_locked()
        self._count("read_cache_evictions", ev)

    def enabled(self) -> bool:
        return self._max_bytes > 0

    # -- data path ---------------------------------------------------------
    def get(self, key, newest_ver):
        """Return (data, size) for `key` iff the cached version matches
        the PG log's newest version for the oid; anything else — absent,
        unvalidatable (no log row), or stale — is a miss (a stale entry
        is dropped on the spot)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self._misses += 1
                return None
            ver, data, size = ent
            if newest_ver is None or ver != newest_ver:
                self._entries.pop(key, None)
                self._bytes -= len(data)
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return data, size

    def put(self, key, ver, data: bytes, size: int) -> None:
        if ver is None:
            return
        with self._lock:
            if self._max_bytes <= 0 or len(data) > self._max_bytes:
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[1])
            self._entries[key] = (ver, data, size)
            self._bytes += len(data)
            self._inserts += 1
            ev = self._evict_locked()
        self._count("read_cache_evictions", ev)

    def invalidate(self, key) -> None:
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= len(ent[1])
                self._invalidations += 1
        if ent is not None:
            self._count("read_cache_invalidations", 1)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- internals ---------------------------------------------------------
    def _evict_locked(self) -> int:
        ev = 0
        while self._bytes > self._max_bytes and self._entries:
            _, (_, data, _) = self._entries.popitem(last=False)
            self._bytes -= len(data)
            self._evictions += 1
            ev += 1
        return ev

    def _count(self, name: str, n: int) -> None:
        if n and self._logger is not None:
            self._logger.inc(name, n)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self._max_bytes,
                "hits": self._hits,
                "misses": self._misses,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "invalidations": self._invalidations,
            }
