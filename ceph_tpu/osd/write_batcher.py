"""WriteBatcher — the coalescing encode layer in front of the GF codec
(ROADMAP "Batched async write path end-to-end"; arXiv:1709.05365's
finding that online-EC system throughput is dominated by the queueing/
batching structure in FRONT of the codec, not the codec itself).

Every EC client write used to walk the stack alone and hand the codec a
single [k, L] stripe; the TPU kernel only earns its throughput when
stripes arrive in fat batches.  The batcher aggregates concurrent
encode requests into multi-stripe batches and performs ONE fused
pack -> apply_matrix -> scatter per flush:

    op A  [k, L] ─┐
    op B  [k, L] ─┼─ concat ─> [k, B*L] ── apply_matrix_jax ──> [m, B*L]
    op C  [k, L] ─┘                                   │
          ^ per-op parity slices demuxed back ────────┘

GF matrix application is byte-column-local (the same property the RMW
parity delta rests on), so the fused parity bytes are BIT-IDENTICAL to
the per-op path — batching changes scheduling, never results.  Each op
blocks for its own slice, so ack/ordering/rollback semantics upstream
(version assignment, sub-op fan-out, dup detection) are untouched.

Flush policy is NIC-interrupt-coalescing shaped, two timers + caps:

- size/byte caps (``ec_batch_max_stripes`` / ``ec_batch_max_bytes``)
  flush immediately when reached;
- an ABSOLUTE window (``ec_batch_window_ms``) bounds how long the
  batch's first stripe may wait;
- an INTER-ARRIVAL gap (window/8) flushes as soon as the queue stops
  growing — closed-loop writers (every in-flight op already queued)
  flush at once instead of idling out the window, while open-load
  bursts still accumulate fat batches.

Backpressure: admission into the batcher rides a ``Throttle``
(common/throttle.py) capped at a few windows of queue bytes.  A full
queue blocks the submitting op thread BEFORE it queues more work; the
blocked op holds its slot in the client's ``objecter_inflight_ops`` /
``objecter_inflight_op_bytes`` admission window, so sustained overload
propagates all the way back and new client writes block at admission,
not mid-pipeline.

A flush larger than ``ec_batch_max_bytes`` (shutdown drains, bursty
arrivals) is split on stripe boundaries and streamed through
``ops.pipeline.stream_encode`` so host->device DMA of device-batch i+1
overlaps the kernel computing device-batch i.

cephdma — the fully async encode path: with the device-resident stripe
pool on (``ec_device_pool``, default; ``ops/device_pool.py``) a flush
packs stripes straight into pooled device buffers (device-side concat —
no host staging copy), encodes through the DONATED jit (the packed
buffer's storage is recycled for the kernel's output where the backend
supports donation), demuxes per-stripe parity as device-side slices,
and completes WITHOUT materializing anything on the host — the single
deliberate sync is each op's ``encode_wait`` (the commit point), which
fetches just its own slice and returns dead device buffers to the pool.
Kernel telemetry separates the two seams: ``ec_batch_flush`` carries
the flush's host-copy bytes (pool ON: transfers only; OFF: pack +
transfer + fetch — the control the ci_gate compares), ``encode_wait``
carries the commit-point sync bytes.  The pool is bypassed — the
historical synchronous path — when ``ec_device_pool=false`` or the
backend sentinel has latched degraded.

Fault injection: the ``osd.write_batcher.flush`` failpoint fires at the
head of every flush.  ``error`` fails EVERY op in the batch (none acks
— the thrasher's no-acked-write-loss invariant holds because the
clients see the failure); ``delay(s)`` stalls the flush; ``crash``
additionally latches the batcher off, after which submits fall back to
inline per-op encode.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..common.failpoint import FailpointCrash, failpoint
from ..common.kernel_telemetry import TELEMETRY
from ..common.lockdep import make_lock
from ..common.throttle import Throttle
from ..common.tracer import TRACER, kernel_annotation, op_trace, trace_now


class _FlushRef:
    """One pooled flush's device-resident parity: the fused [m, B*L]
    parent buffer plus the shared commit state.  The FIRST op to reach
    its encode_wait materializes the whole parent in ONE fetch (a
    single sync + host copy per flush, not per stripe — per-stripe
    device slicing was measured to drown CPU dispatch), caches the host
    array for its batch-mates, and returns the parent buffer to the
    device pool."""

    #: bound on waiting out another op's in-flight fetch
    FETCH_TIMEOUT = 60.0

    __slots__ = ("parent", "host", "error", "fetch_bytes", "_claim",
                 "_ready")

    def __init__(self, parent):
        self.parent = parent
        self.host: np.ndarray | None = None
        self.error: BaseException | None = None
        self.fetch_bytes = 0  # set once, by the fetching op
        self._claim = make_lock("osd::wb_flush_ref")
        self._ready = threading.Event()

    def prefetch(self) -> None:
        """Start the device->host transfer WITHOUT blocking (the
        flusher calls this BEFORE completing the batch, so no op can
        have consumed the parent yet): by the time an op commits, the
        bytes are in flight or landed and the elected fetcher's
        np.asarray doesn't pin a client thread for the whole kernel."""
        dev = self.parent
        if dev is None:
            return
        try:
            dev.copy_to_host_async()
        except Exception:  # noqa: CL7 — best-effort warm-up: no async D2H on this array/backend, the commit fetch just pays full price
            pass

    def fetch(self) -> tuple[np.ndarray, bool]:
        """The commit-point materialization: ONE op is elected to fetch
        and everyone else waits on a broadcast Event — batch-mates wake
        in a burst, not a lock-handoff trickle (the trickle was measured
        to starve the NEXT flush's coalescing window).  A fetch failure
        (the async path surfaces deferred device errors HERE) is latched
        and re-raised to every batch-mate.  Returns (host parity of the
        whole flush, did-I-pay-for-the-fetch)."""
        from ..ops.device_pool import POOL

        if self.host is None and self.error is None \
                and self._claim.acquire(blocking=False):
            try:
                if self.host is None and self.error is None:
                    # noqa: CL2 — parent is only ever touched by the
                    # thread holding _claim (try-acquire above; CL2
                    # can't see a non-`with` acquire)
                    dev, self.parent = self.parent, None  # noqa: CL2
                    try:
                        host = np.asarray(dev, dtype=np.uint8)  # noqa: CL8 — THE commit-point sync
                    except BaseException as e:
                        self.error = e
                        self._ready.set()
                        raise
                    self.fetch_bytes = host.nbytes
                    self.host = host
                    # broadcast BEFORE the pool bookkeeping: 63 batch-
                    # mates may be parked on this event
                    self._ready.set()
                    POOL.release(dev)
                    return host, True
            finally:
                self._claim.release()
        if self.host is None and self.error is None \
                and not self._ready.wait(self.FETCH_TIMEOUT):
            raise TimeoutError("flush parity fetch never completed")
        if self.error is not None:
            raise self.error
        return self.host, False


class _DevParity:
    """A stripe's parity still resident on device (the pooled async
    path): column window [c0, c1) of its flush's fused parity,
    materialized host-side only at the op's encode_wait."""

    __slots__ = ("ref", "c0", "c1", "rows")

    def __init__(self, ref: _FlushRef, c0: int, c1: int, rows: int):
        self.ref = ref
        self.c0 = c0
        self.c1 = c1
        self.rows = rows

    @property
    def nbytes(self) -> int:
        return self.rows * (self.c1 - self.c0)


class _PendingStripe:
    """One op's stripe riding a batch: input chunks in, parity (or the
    batch's error) out.  Completion rides a PER-OP Event rather than the
    batcher's shared condition: a notify_all on a shared condition wakes
    every waiter on every arrival AND every completion (a thundering
    herd that was measured to eat the whole batching win at 8+ clients),
    while an Event wakes exactly its own op.  The Event's internal lock
    is the publish edge ordering the flusher's parity write before the
    submitter's read."""

    __slots__ = ("key", "mat", "mat_key", "chunks", "nbytes", "arrival",
                 "event", "parity", "error", "admitted", "tctx",
                 "tracked", "acct", "queued_at", "share_key")

    def __init__(self, mat: np.ndarray, chunks: np.ndarray,
                 mat_key: str | None = None):
        self.mat = mat
        # stable digest of mat held on the codec (cephdma satellite: no
        # fresh mat.tobytes() host copy per stripe to key the group)
        self.mat_key = mat_key
        self.chunks = chunks
        # fuse only stripes encoding under the same matrix at the same
        # chunk length: concat along columns is exact for those
        self.key = (mat_key if mat_key is not None else mat.tobytes(),
                    chunks.shape[1])
        self.nbytes = chunks.nbytes
        self.arrival = time.monotonic()
        self.event = threading.Event()
        self.parity: np.ndarray | None = None
        self.error: BaseException | None = None
        self.admitted = False  # holds admission-throttle budget
        # cephtrace: the submitting op's context rides the stripe so the
        # flusher (a different thread) can attribute queue/encode spans
        self.tctx = None
        self.tracked = None
        # cephmeter: (table, client, pool) identity the OSD stamped into
        # the op-trace state — per-client admission/queue attribution
        self.acct = None
        self.queued_at = 0.0  # trace_now clock, for the queue-stage span
        # cephqos: (client, pool) whose per-client admission share this
        # stripe's bytes count against (None = identity-less submit)
        self.share_key = None


class WriteBatcher:
    """Multi-stripe encode coalescer (see module docstring).

    ``encode_chunks(mat, chunks)`` is the one entry point: [k, L] byte
    chunks in, [m, L] parity out, blocking until the op's batch flushed.
    Callers that are not plain column-local matrix applies must not come
    here (the OSD's ``_batch_matrix`` eligibility gate).
    """

    #: admission throttle holds this many byte-caps of queued stripes
    QUEUE_WINDOWS = 4
    #: ceiling on one op's wait for admission into a saturated queue
    ADMIT_TIMEOUT = 30.0
    #: ceiling on one op's wait for its flush (window + device time)
    OP_TIMEOUT = 60.0

    def __init__(self, cct, logger=None, entity: str = ""):
        self._cct = cct
        self._logger = logger
        self._entity = entity or (cct.name if cct is not None else "")
        self._lock = make_lock("osd::write_batcher")
        self._cond = threading.Condition(self._lock)
        self._queue: list[_PendingStripe] = []
        self._queued_bytes = 0
        self._flush_asap = False
        self._stop_flag = False
        self._crashed = False
        self._thread: threading.Thread | None = None
        self._admission = Throttle(
            "write_batcher::queue",
            self._max_bytes() * self.QUEUE_WINDOWS,
        )
        # own counters so standalone users (bench) see stats without a
        # PerfCounters registry; the OSD's logger mirrors them
        self._stats = {"flushes": 0, "stripes": 0, "bytes": 0, "inline": 0,
                       "share_waits": 0}
        # cephqos: admission bytes currently held per (client, pool) —
        # the per-client share gate reads/writes this under self._lock;
        # _share_waiters counts gate sleepers so releases only notify
        # when someone is actually parked (a no-waiter notify is noise
        # to the flusher and to cephrace's lost-wakeup heuristic)
        self._held: dict[tuple, int] = {}
        self._share_waiters = 0
        # fan-in tag tying one fused encode's many per-op spans together;
        # touched only by the single flusher thread
        self._flush_seq = 0

    def _release_share(self, p: _PendingStripe) -> None:
        """Return one stripe's bytes to its client's admission share and
        wake share-gate waiters (idempotent via share_key clearing)."""
        key = p.share_key
        if key is None:
            return
        p.share_key = None
        with self._cond:
            left = self._held.get(key, 0) - p.nbytes
            if left > 0:
                self._held[key] = left
            else:
                self._held.pop(key, None)
            if self._share_waiters:
                self._cond.notify_all()

    # -- config (runtime-changeable: read per use) -------------------------
    def _window(self) -> float:
        if self._cct is None:
            return 0.0
        return max(0.0, float(self._cct.conf.get("ec_batch_window_ms"))) / 1e3

    def _max_stripes(self) -> int:
        if self._cct is None:
            return 1
        return max(1, int(self._cct.conf.get("ec_batch_max_stripes")))

    def _max_bytes(self) -> int:
        if self._cct is None:
            return 0
        return max(0, int(self._cct.conf.get("ec_batch_max_bytes")))

    def _client_share(self, cap: int) -> int:
        """Per-(client,pool) admission-share cap in bytes (cephqos);
        0 = disabled (no cct, unbounded queue, or share >= 1.0)."""
        if self._cct is None or cap <= 0:
            return 0
        frac = float(self._cct.conf.get("ec_batch_client_max_share"))
        if frac >= 1.0:
            return 0
        return max(1, int(cap * frac))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._thread is not None:
                return
            self._stop_flag = False
            self._thread = threading.Thread(
                target=self._flush_loop,
                name=f"{self._entity}-wb-flush", daemon=True,
            )
        self._thread.start()

    def stop(self) -> None:
        """Drain-and-stop: queued stripes are flushed (shutdown flush),
        then the flusher exits; later submits encode inline."""
        with self._cond:
            self._stop_flag = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)

    def coalescing(self) -> bool:
        """True when submits will be batched rather than encoded inline."""
        with self._lock:
            return (self._thread is not None and not self._stop_flag
                    and not self._crashed) and self._window() > 0.0

    # -- introspection (tests / bench) -------------------------------------
    @property
    def admission(self) -> Throttle:
        return self._admission

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def flush_now(self) -> None:
        """Force the current queue out without waiting for window/caps."""
        with self._cond:
            self._flush_asap = True
            self._cond.notify_all()

    def _use_pool(self) -> bool:
        """Pooled async flush path usable right now: the runtime escape
        hatch (``ec_device_pool``) AND the process-wide pool's own gate
        (configured on, sentinel not degraded)."""
        from ..ops.device_pool import POOL

        if self._cct is not None \
                and not bool(self._cct.conf.get("ec_device_pool")):
            return False
        return POOL.enabled()

    # -- submit ------------------------------------------------------------
    def encode_chunks(self, mat: np.ndarray, chunks: np.ndarray,
                      mat_key: str | None = None) -> np.ndarray:
        """[k, L] data chunks -> [m, L] parity, bit-identical to
        ``apply_matrix_jax(mat, chunks)``; blocks until this stripe's
        batch flushed (or encodes inline when coalescing is off)."""
        return self.encode_wait(self.encode_submit(mat, chunks, mat_key))

    def encode_submit(self, mat: np.ndarray, chunks: np.ndarray,
                      mat_key: str | None = None) -> _PendingStripe:
        """Queue one [k, L] stripe for coalesced encode and return its
        ticket.  Every ticket MUST be passed to encode_wait (it holds
        admission-throttle budget until then).  Async clients keep a
        small window of tickets in flight — that window is what lets a
        single writer's stripes coalesce with its own, not only with
        other writers'.  ``mat_key``: the codec's precomputed stable
        digest of ``mat`` (ops.bitplane.matrix_digest) — group keying
        and the device bitmatrix cache then skip the per-stripe
        ``mat.tobytes()`` host copy."""
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        p = _PendingStripe(mat, chunks, mat_key)
        st = op_trace()
        if st is not None:
            if TRACER.enabled:  # one attribute check when tracing is off
                p.tctx = st.get("ctx")
            p.tracked = st.get("tracked")
            p.acct = st.get("acct")
        if not self.coalescing():
            p.parity = self._inline(mat, chunks, tctx=p.tctx,
                                    tracked=p.tracked, mat_key=mat_key)
            p.event.set()
            return p
        # backpressure: block HERE, at admission, while the queue is
        # saturated — the op thread's inflight budget upstream is what
        # carries the stall back to the client's admission throttle
        cap = self._max_bytes() * self.QUEUE_WINDOWS
        if cap != self._admission.max:
            self._admission.reset_max(cap)
        t_adm0 = trace_now()
        # cephqos per-client share gate BEFORE the global FIFO: one bulk
        # streamer's bytes cap out at share*cap, so a small writer's
        # stripe never queues behind a wall of someone else's budget.
        # An op past its own share waits for its OWN earlier bytes to
        # drain (at least one stripe always fits — no self-deadlock);
        # stop/crash pass the gate and take the inline path below.
        share = self._client_share(cap)
        key = tuple(p.acct[1:]) if p.acct is not None else None
        if share > 0 and key is not None:
            with self._cond:
                if self._held.get(key, 0) + p.nbytes > max(share, p.nbytes):
                    self._stats["share_waits"] += 1
                    self._share_waiters += 1
                    try:
                        ok = self._cond.wait_for(
                            lambda: (self._stop_flag or self._crashed
                                     or self._held.get(key, 0) + p.nbytes
                                     <= max(share, p.nbytes)),
                            timeout=self.ADMIT_TIMEOUT)
                    finally:
                        self._share_waiters -= 1
                    if not ok:
                        raise IOError(
                            f"write batcher per-client share timed out "
                            f"({self._held.get(key, 0)} B held by {key}, "
                            f"share {share} B)")
                # reserve inside the critical section (two threads of
                # one client must not both pass the check unreserved);
                # released by encode_wait, or below on admission timeout
                self._held[key] = self._held.get(key, 0) + p.nbytes
            p.share_key = key
        if not self._admission.get(p.nbytes, timeout=self.ADMIT_TIMEOUT):
            self._release_share(p)
            raise IOError(
                f"write batcher admission timed out "
                f"({self._admission.current} B queued, cap {cap} B)"
            )
        p.admitted = True
        try:
            t_adm1 = trace_now()
            if self._logger is not None:
                self._logger.hinc("stage_admission", t_adm1 - t_adm0)
            if p.acct is not None:
                tab, client, pool = p.acct
                tab.record_stage(client, pool, "admission",
                                 t_adm1 - t_adm0)
            if p.tracked is not None:
                p.tracked.stage_add("admission", t_adm1 - t_adm0)
            if p.tctx is not None:
                TRACER.record(p.tctx, "admission", entity=self._entity,
                              t0=t_adm0, t1=t_adm1, nbytes=p.nbytes)
                if p.tracked is not None:
                    p.tracked.mark_event("admission", ts=t_adm1)
            p.queued_at = t_adm1
            enqueued = False
            with self._cond:
                if not (self._stop_flag or self._crashed):
                    enqueued = True
                    self._queue.append(p)
                    self._queued_bytes += p.nbytes
                    # only the flusher waits on the shared condition;
                    # per-op completion rides p.event (no herd)
                    self._cond.notify_all()
            if not enqueued:  # raced a stop/crash: encode inline
                p.parity = self._inline(p.mat, p.chunks, tctx=p.tctx,
                                        tracked=p.tracked,
                                        mat_key=p.mat_key)
                p.event.set()
            return p
        except Exception:
            # nobody will encode_wait() a ticket whose submit raised —
            # hand the admission slot and share back before escaping,
            # or the throttle pins at its cap under sustained errors
            p.admitted = False
            self._admission.put(p.nbytes)
            self._release_share(p)
            raise

    def encode_wait(self, p: _PendingStripe) -> np.ndarray:
        """Block for a ticket's parity (or raise its batch's error).

        THE commit point of the async encode path: a pooled flush left
        this op's parity device-resident, and the ``np.asarray`` here is
        the one deliberate host materialization — per op, off the
        flusher thread, accounted as the ``encode_wait`` sync-point
        kernel record.  The last stripe of a flush to commit returns the
        flush's parity buffer to the device pool."""
        try:
            if not p.event.wait(timeout=self.OP_TIMEOUT):
                raise TimeoutError(
                    f"write batcher flush of {p.nbytes} B stripe timed "
                    f"out after {self.OP_TIMEOUT}s"
                )
            if p.tracked is not None:
                # dump_historic_ops offset for the encode stage, same
                # trace_now clock the flusher's span boundaries use
                p.tracked.mark_event("encode", ts=trace_now())
            if p.error is not None:
                raise p.error
            if isinstance(p.parity, _DevParity):
                p.parity = self._commit_fetch(p.parity)
            return p.parity
        finally:
            if p.admitted:
                p.admitted = False
                self._admission.put(p.nbytes)
            self._release_share(p)

    def _commit_fetch(self, dp: _DevParity) -> np.ndarray:
        """Materialize one op's device-resident parity (the deliberate
        commit sync): the flush's shared fetch runs at most once; this
        op then slices its own column window host-side."""
        t0 = time.perf_counter()
        full, fetched = dp.ref.fetch()
        if fetched and TELEMETRY.enabled:
            # ONE record per flush, by the op that paid the fetch — its
            # batch-mates' waits are free host slices, and recording
            # each of them was measured to cost real throughput at
            # 10k+ ops/s (the counters lock per record)
            from ..ops.bitplane import current_backend

            TELEMETRY.record(
                "encode_wait", current_backend(),
                time.perf_counter() - t0,
                bytes_out=dp.ref.fetch_bytes, synced=True,
                host_copy_bytes=dp.ref.fetch_bytes)
        return full[:, dp.c0:dp.c1]

    def _inline(self, mat: np.ndarray, chunks: np.ndarray,
                tctx=None, tracked=None,
                mat_key: str | None = None) -> np.ndarray:
        from ..ops.bitplane import apply_matrix_jax

        with self._lock:
            self._stats["inline"] += 1
        if self._logger is not None:
            self._logger.inc("ec_batch_inline")
        t0 = trace_now()
        with kernel_annotation(
            "ec_encode_inline", (tctx.trace_id,) if tctx is not None else ()
        ):
            parity = np.asarray(  # noqa: CL8 — inline per-op encode is deliberately synchronous
                apply_matrix_jax(mat, chunks, mat_key=mat_key),
                dtype=np.uint8)
        if tctx is not None:
            TRACER.record(tctx, "encode", entity=self._entity,
                          t0=t0, t1=trace_now(), inline=True)
        if tracked is not None:
            tracked.stage_add("encode", trace_now() - t0)
        if self._logger is not None:
            self._logger.hinc("stage_encode", trace_now() - t0)
        return parity

    # -- flusher -----------------------------------------------------------
    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop_flag:
                    self._cond.wait(timeout=0.5)
                if not self._queue:
                    return  # stopped and drained
                self._wait_for_batch_locked()
                batch = self._queue
                self._queue = []
                self._queued_bytes = 0
                self._flush_asap = False
            try:
                self._flush_batch(batch)
            except Exception as e:  # belt: the flusher must never die
                if self._cct is not None:
                    self._cct.dout("osd", 0,
                                   f"{self._entity} write batcher flush "
                                   f"raised: {e!r}")
                self._complete(batch, err=e)

    def _wait_for_batch_locked(self) -> None:
        """Coalescing wait (lock held): returns once the batch should
        flush — caps reached, absolute window expired, an inter-arrival
        gap passed with no growth, or stop/flush_now."""
        window = self._window()
        max_stripes = self._max_stripes()
        max_bytes = self._max_bytes()
        first = self._queue[0].arrival
        gap = max(window / 8.0, 5e-5)
        while (
            not self._stop_flag
            and not self._flush_asap
            and len(self._queue) < max_stripes
            and (max_bytes <= 0 or self._queued_bytes < max_bytes)
        ):
            remain = first + window - time.monotonic()
            if remain <= 0:
                break
            n0 = len(self._queue)
            self._cond.wait(timeout=min(remain, gap))
            if len(self._queue) == n0:
                break  # quiescent: every in-flight writer already queued

    def _flush_batch(self, batch: list[_PendingStripe]) -> None:
        t0 = time.perf_counter()
        w0 = trace_now()
        traced = [p for p in batch if p.tctx is not None]
        # queue stage: stripe admitted -> flush started
        for p in batch:
            if not p.queued_at:
                continue
            q_dur = max(0.0, w0 - p.queued_at)
            if self._logger is not None:
                self._logger.hinc("stage_queue", q_dur)
            if p.acct is not None:
                tab, client, pool = p.acct
                tab.record_stage(client, pool, "queue", q_dur)
            if p.tracked is not None:
                p.tracked.stage_add("queue", q_dur)
        for p in traced:
            TRACER.record(p.tctx, "queue", entity=self._entity,
                          t0=p.queued_at or w0, t1=w0)
        err: BaseException | None = None
        try:
            failpoint("osd.write_batcher.flush", cct=self._cct,
                      entity=self._entity, stripes=len(batch))
        except FailpointCrash as e:
            # simulated death of the encode stage: fail the batch and
            # latch coalescing off — later submits encode inline
            with self._cond:
                self._crashed = True
            err = e
        except Exception as e:
            err = e
        results: list[tuple[_PendingStripe, object]] = []
        host_copy = 0
        flush_synced = False
        if err is None:
            try:
                results, host_copy, flush_synced = \
                    self._encode_groups(batch)
            except Exception as e:
                err = e
        w1 = trace_now()
        if err is None:
            for p in batch:
                if p.tracked is not None:
                    p.tracked.stage_add("encode", w1 - w0)
        if err is None and traced:
            # ONE fused-encode flush, MANY op spans: the fan-in is
            # expressed as one "encode" span per participating trace
            # (parent = that op's ctx, so every tree stays connected)
            # all sharing a flush_id + fan_in tag
            with self._lock:
                self._flush_seq += 1
                fid = self._flush_seq
            fan_in = len({p.tctx.trace_id for p in traced})
            seen: set[str] = set()
            for p in traced:
                if p.tctx.trace_id in seen:
                    continue  # one op may batch several stripes
                seen.add(p.tctx.trace_id)
                TRACER.record(
                    p.tctx, "encode", entity=self._entity, t0=w0, t1=w1,
                    flush_id=fid, stripes=len(batch), fan_in=fan_in,
                )
        if err is None:
            # pooled flushes: start each parity parent's D2H in the
            # background so commit fetches land on warm bytes.  MUST
            # run before _complete — once events are set an op may
            # consume the parent (fetch swaps it out and recycles it)
            seen_refs: set[int] = set()
            for _p, r in results:
                if isinstance(r, _DevParity) and id(r.ref) not in seen_refs:
                    seen_refs.add(id(r.ref))
                    r.ref.prefetch()
        self._complete(batch, err=err, results=results)
        if err is None:
            nbytes = sum(p.nbytes for p in batch)
            with self._lock:
                self._stats["flushes"] += 1
                self._stats["stripes"] += len(batch)
                self._stats["bytes"] += nbytes
            if self._logger is not None:
                self._logger.inc("ec_batch_flushes")
                self._logger.inc("ec_batch_stripes", len(batch))
                self._logger.inc("ec_batch_bytes", nbytes)
                self._logger.tinc("ec_batch_flush_latency",
                                  time.perf_counter() - t0)
                self._logger.hinc("stage_encode", w1 - w0)
            if TELEMETRY.enabled:
                # pool OFF: the flush fetched every parity slice, a
                # true sync point — honest achieved GiB/s for the fused
                # pack -> encode -> scatter.  Pool ON: dispatch is
                # async (synced=False, the record measures the queue;
                # the commit-point sync rides the per-op `encode_wait`
                # record instead), and host_copy carries only the
                # copies THIS flush actually performed — the
                # control-vs-pool delta the ci_gate smoke compares.
                from ..ops.bitplane import current_backend

                TELEMETRY.record(
                    "ec_batch_flush", current_backend(),
                    time.perf_counter() - t0, bytes_in=nbytes,
                    bytes_out=sum(int(r[1].nbytes) for r in results),
                    synced=flush_synced, host_copy_bytes=host_copy)

    def _encode_groups(
        self, batch: list[_PendingStripe]
    ) -> tuple[list[tuple[_PendingStripe, object]], int, bool]:
        """One fused pack -> encode -> scatter per (matrix, L) group.

        Returns (results, host_copy_bytes, synced): with the device pool
        ON the results are `_DevParity` slices still resident on device
        (nothing materialized — host_copy counts only the host->device
        stripe commits and synced stays False, the dispatch is async);
        with it OFF this is the historical synchronous path (host pack
        copy + packed transfer + full parity fetch, all counted, synced
        True).  Parity bytes are bit-identical either way — pooling
        changes scheduling and allocation, never results."""
        groups: dict[tuple, list[_PendingStripe]] = {}
        for p in batch:
            groups.setdefault(p.key, []).append(p)
        max_bytes = self._max_bytes()
        use_pool = self._use_pool()
        host_copy = 0
        synced = False
        out: list[tuple[_PendingStripe, object]] = []
        for (_gkey, L), ps in groups.items():
            mat = ps[0].mat
            stripe_b = ps[0].chunks.nbytes
            group_b = sum(p.chunks.nbytes for p in ps)
            if max_bytes > 0 and len(ps) > 1 and group_b > max_bytes:
                # burst bigger than one device batch: split on stripe
                # boundaries and double-buffer DMA against compute
                # (stream_encode pools its own transfers; its result
                # fetches make this group a sync point either way)
                from ..ops.pipeline import stream_encode

                packed = np.concatenate([p.chunks for p in ps], axis=1)
                spd = max(1, max_bytes // stripe_b)

                def dev_batches(packed=packed, L=L, n=len(ps), spd=spd):
                    for i in range(0, n, spd):
                        yield packed[:, i * L:(i + spd) * L]

                outs = stream_encode(mat, dev_batches(), kernel="auto",
                                     mat_key=ps[0].mat_key)
                parity = np.concatenate(outs, axis=1)
                # only THIS seam's own copies (the two host concats):
                # the transfers and result fetches are counted by the
                # stream_encode record — each seam counts its own
                host_copy += packed.nbytes + parity.nbytes
                synced = True
                for i, p in enumerate(ps):
                    out.append((p, parity[:, i * L:(i + 1) * L]))
                continue
            if use_pool:
                # cephdma pooled async path: commit + concat + encode
                # fuse into ONE dispatch (no host staging pack — the
                # stripes' committed buffers are donated straight into
                # the kernel), parity stays device-resident; the op's
                # encode_wait owns the single deliberate sync, and the
                # parent parity buffer recycles through the pool there
                from ..ops.bitplane import fused_bucket, fused_encode_async

                parity_dev = fused_encode_async(
                    mat, [p.chunks for p in ps],
                    mat_key=ps[0].mat_key, donate=True)
                # the host->device stripe commits — charged at the
                # dispatched arity (zero-stripe pads transfer too)
                host_copy += fused_bucket(len(ps)) * stripe_b
                ref = _FlushRef(parity_dev)
                m_rows = mat.shape[0]
                for i, p in enumerate(ps):
                    out.append((p, _DevParity(
                        ref, i * L, (i + 1) * L, m_rows)))
                continue
            # historical synchronous path (ec_device_pool=false escape
            # hatch / sentinel-degraded backend): host pack, transfer,
            # full parity fetch right here on the flusher
            from ..ops.bitplane import apply_matrix_jax

            packed = (ps[0].chunks if len(ps) == 1 else
                      np.concatenate([p.chunks for p in ps], axis=1))
            parity = np.asarray(  # noqa: CL8 — the pool-off flush IS the sync point
                apply_matrix_jax(mat, packed, mat_key=ps[0].mat_key),
                dtype=np.uint8)
            host_copy += (packed.nbytes if len(ps) > 1 else 0) \
                + packed.nbytes + parity.nbytes
            synced = True
            for i, p in enumerate(ps):
                out.append((p, parity[:, i * L:(i + 1) * L]))
        return out, host_copy, synced

    def _complete(self, batch: list[_PendingStripe],
                  err: BaseException | None = None,
                  results: list[tuple[_PendingStripe, object]] = ()):
        if err is not None:
            for p in batch:
                p.error = err
                p.event.set()
        else:
            for p, parity in results:
                p.parity = parity
                p.event.set()
