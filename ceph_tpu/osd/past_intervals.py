"""PastIntervals — per-PG history of closed up/acting intervals
(reference: src/osd/osd_types.h :: PastIntervals / pg_interval_t,
maintained by PastIntervals::check_new_interval, consumed by
PeeringState::build_prior and choose_acting; round-3 verdict task #7).

Why intervals and not just version numbers: after a sequence of
failovers, the OSD with the HIGHEST pg version is not necessarily
reachable from the current acting set, and the current acting set's
own versions prove nothing about writes that happened in an interval
none of them served.  The interval history answers two questions the
generation floors cannot:

1. *Completeness* — may this primary activate?  Only if, for every past
   interval that could have accepted writes (`maybe_went_rw`), at least
   one member has been queried: an unqueried rw interval may hold the
   authoritative log (build_prior's down-osds-we-would-probe blocking).
2. *Where to look* — which non-acting OSDs are worth probing for stray
   chunks/logs?  Exactly the members of past rw intervals, per shard —
   not the whole OSD map (this bounds _probe_stray's former global
   walk).

Intervals are recorded at map-change time on each OSD hosting the PG,
persisted in the PG meta omap, and pruned when the PG goes fully clean
in the current interval (the reference prunes at last_epoch_clean).
"""
from __future__ import annotations

import json

# history cap: a PG that somehow never goes clean must not grow meta
# without bound; the newest intervals are the ones that matter
MAX_INTERVALS = 64


class PastIntervals:
    def __init__(self):
        # newest-last list of {"first", "last", "up", "acting",
        # "primary", "maybe_went_rw"}
        self.intervals: list[dict] = []

    # -- maintenance -------------------------------------------------------
    def add(self, first: int, last: int, up: list[int], acting: list[int],
            primary: int, maybe_went_rw: bool) -> None:
        """Record a CLOSED interval (reference: check_new_interval)."""
        self.intervals.append({
            "first": int(first), "last": int(last),
            "up": [int(o) for o in up],
            "acting": [int(o) for o in acting],
            "primary": int(primary),
            "maybe_went_rw": bool(maybe_went_rw),
        })
        if len(self.intervals) > MAX_INTERVALS:
            del self.intervals[: len(self.intervals) - MAX_INTERVALS]

    def clear(self) -> None:
        self.intervals = []

    def __len__(self) -> int:
        return len(self.intervals)

    def __bool__(self) -> bool:
        return bool(self.intervals)

    # -- queries -----------------------------------------------------------
    def prior_holders(self, exclude: set[int]) -> dict[int, int]:
        """{osd: shard-it-held} over every past rw interval, newest
        first (so an OSD that held different shards across intervals
        reports its most recent role) — the choose_acting candidate
        pool beyond the current acting set."""
        out: dict[int, int] = {}
        for iv in reversed(self.intervals):
            if not iv["maybe_went_rw"]:
                continue
            for shard, osd in enumerate(iv["acting"]):
                if osd >= 0 and osd not in exclude and osd not in out:
                    out[osd] = shard
        return out

    def query_candidates(self, exclude: set[int], is_up,
                         cap: int = 16) -> dict[int, int]:
        """{osd: shard} to query this peering round, chosen so that EVERY
        past rw interval with an up member contributes at least one
        candidate — a flat newest-N cut could starve an old interval
        forever and wedge the blocked_by gate (review r4).  Newest
        intervals still get priority within the cap."""
        out: dict[int, int] = {}
        for iv in reversed(self.intervals):
            if not iv["maybe_went_rw"]:
                continue
            members = [
                (shard, osd) for shard, osd in enumerate(iv["acting"])
                if osd >= 0 and osd not in exclude and is_up(osd)
            ]
            if any(osd in out for _s, osd in members):
                continue  # interval already covered
            for shard, osd in members:
                if len(out) >= cap:
                    # cap reached: still admit ONE member so this
                    # interval is not starved
                    out.setdefault(osd, shard)
                    break
                out[osd] = shard
        return out

    def holders_of_shard(self, shard: int, exclude: set[int]) -> list[int]:
        """OSDs that held `shard` in any past rw interval, newest first —
        the bounded candidate list for stray-chunk probes."""
        out: list[int] = []
        for iv in reversed(self.intervals):
            if not iv["maybe_went_rw"]:
                continue
            acting = iv["acting"]
            if shard < len(acting):
                osd = acting[shard]
                if osd >= 0 and osd not in exclude and osd not in out:
                    out.append(osd)
        return out

    def blocked_by(self, queried: set[int]) -> list[dict]:
        """Past rw intervals NONE of whose acting members was queried
        this peering round (build_prior's blocking condition): each may
        hold the authoritative log, so activating without hearing from
        any member risks serving a forked or stale history.  Returns the
        offending intervals (empty = safe to activate).  Down members
        block too — that is the point: their unheard history is exactly
        the risk."""
        out = []
        for iv in self.intervals:
            if not iv["maybe_went_rw"]:
                continue
            members = {o for o in iv["acting"] if o >= 0}
            if members and not (members & queried):
                out.append(iv)
        return out

    # -- persistence -------------------------------------------------------
    def to_bytes(self) -> bytes:
        return json.dumps(self.intervals).encode()

    @classmethod
    def from_bytes(cls, raw: bytes | None) -> "PastIntervals":
        pi = cls()
        if raw:
            try:
                ivs = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                ivs = []
            if isinstance(ivs, list):
                pi.intervals = [iv for iv in ivs if isinstance(iv, dict)]
        return pi
