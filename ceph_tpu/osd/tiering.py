"""Cache-tier front-end, promote/flush/evict, and the tier agent (reference: PrimaryLogPG::maybe_handle_cache_detail, agent_work).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations




from ..store.object_store import NotFound, Transaction
from .messages import (
    MOSDOp,
    MOSDOpReply,
    pack_data,
)
from ..osd.osdmap import object_ps
from .pg import CLONE_SEP, MUTATING_OPS


class TieringMixin:
    # -- cache tiering (reference: PrimaryLogPG::maybe_handle_cache_detail
    # — promote_object / do_proxy_read / whiteouts — plus the TierAgent
    # flush/evict loop in PrimaryLogPG::agent_work) -----------------------
    #
    # State model (crash-safe by construction): a cache object with the
    # `tier.clean` user xattr is known flushed/promoted-identical to the
    # base copy and may be evicted; an object WITHOUT it is treated as
    # dirty and will be flushed.  Mutations remove the marker BEFORE the
    # data op and flush/promote set it AFTER the content settles, so a
    # crash at any point can only mislabel a clean object as dirty (a
    # harmless re-flush), never a dirty one as clean (which could evict
    # an unflushed write).  The reference carries these as object_info_t
    # FLAG_DIRTY/FLAG_WHITEOUT inside the op transaction; the xattr
    # spelling reuses this repo's replicated-xattr machinery instead.
    # `tier.whiteout` marks a deleted-in-cache stub whose flush deletes
    # the base object.  tier.* xattrs are internal metadata: visible in
    # getxattrs (documented), never copied to the base pool.

    def _tier_client_op(self, pool_id: int, oid: str, op: str,
                        data=None, off: int = 0, length: int = 0):
        """OSD-as-client op against another pool (promote reads, flush
        writes) — targets the named pool directly, the internal analog
        of CEPH_OSD_FLAG_IGNORE_OVERLAY.  Returns the reply or raises
        OSError on timeout/conn failure."""
        m = self.osdmap
        pool = m.pools.get(pool_id) if m else None
        if pool is None:
            raise OSError(f"tier op: no pool {pool_id}")
        ps = object_ps(oid, pool.pg_num)
        _a, primary = self._acting(pool_id, ps)
        if primary < 0:
            raise OSError(f"tier op: pg {pool_id}.{ps} has no primary")
        tid = self._next_tid()
        rep = self._forward_op(primary, MOSDOp(
            tid=tid, pool=pool_id, oid=oid, op=op, data=data,
            epoch=self.my_epoch(), off=off, length=length,
            reqid=f"tier.{self.id}.{tid}" if op in MUTATING_OPS else None,
        ))
        if rep is None:
            raise OSError(f"tier op {op} {oid!r}: no reply")
        return rep

    def _tier_autoclean(self, pool, oid: str) -> bool:
        """True when a mutation of `oid` must clear the tier.clean marker
        ATOMICALLY with its data op (advisor r4: a clean-flag check in the
        staging path races the flush's clean-mark — only a clear inside
        the mutation's own pg.lock transaction closes the window where
        dirty data gets labeled clean and evicted)."""
        if pool is None or pool.tier_of < 0 or pool.cache_mode == "none":
            return False
        return bool(oid) and CLONE_SEP not in oid and \
            not oid.startswith(("_", ":pg:"))

    def _txn_clear_clean(self, t: Transaction, cid: str, oid: str) -> None:
        """Append the primary-local tier.clean removal to a mutation's
        transaction (the replicas get theirs via the sub-op `rmattrs`)."""
        try:
            if "u_tier.clean" in self.store.getattrs(cid, oid):
                t.rmattr(cid, oid, "u_tier.clean")
        except (NotFound, KeyError):
            pass

    def _tier_flag(self, pg, oid: str, flag: str) -> bool:
        cid = self._cid(pg.pgid, 0)
        try:
            return self.store.getattr(cid, oid, f"u_tier.{flag}") == b"1"
        except (NotFound, KeyError):
            return False

    def _tier_mark(self, pg, acting, oid: str, flag: str,
                   value: bool) -> MOSDOpReply:
        """Set/clear a tier.* marker through the replicated xattr path so
        it survives primary failover."""
        return self._xattr_op(pg, acting, 0, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="setxattr",
            data={f"tier.{flag}": pack_data(b"1") if value else None},
            epoch=self.my_epoch(),
        ))

    def _cache_tier_op(self, pg, pool, acting, ps, msg, _depth: int = 0):
        """Cache-pool front-end.  Returns a final MOSDOpReply, or None to
        fall through to normal execution (object staged in the cache).

        A promote that aborts because the object appeared concurrently
        (rc == 1, see _tier_promote's race contract) restarts the whole
        decision: the staged object changes every branch below."""
        base_id = pool.tier_of
        m = self.osdmap
        base_pool = m.pools.get(base_id) if m else None
        oid = msg.oid
        if (
            base_pool is None or not oid or CLONE_SEP in oid
            or oid.startswith(":pg:")
            or msg.op in ("list", "watch", "unwatch", "notify")
            or getattr(msg, "ps", None) is not None  # internal machinery
        ):
            return None

        def retry():
            if _depth >= 3:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result="tier staging kept racing")
            return self._cache_tier_op(pg, pool, acting, ps, msg,
                                       _depth + 1)

        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            present = self.store.exists(cid, oid)
            whiteout = present and self._tier_flag(pg, oid, "whiteout")

        if msg.op == "cache_flush":
            return self._tier_flush_object(pg, pool, acting, oid, msg.tid)
        if msg.op == "cache_evict":
            return self._tier_evict_object(pg, pool, acting, oid, msg.tid)

        mutating = msg.op in MUTATING_OPS
        if not mutating:
            # reads / stat / getxattrs / omap_get
            if whiteout:
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found (whiteout)")
            if present:
                return None
            if pool.cache_mode == "readproxy":
                # proxy without promoting (reference: do_proxy_read)
                try:
                    rep = self._tier_client_op(
                        base_id, oid, msg.op, data=msg.data,
                        off=msg.off or 0, length=msg.length or 0,
                    )
                except OSError as e:
                    return MOSDOpReply(tid=msg.tid, retval=-11,
                                       epoch=self.my_epoch(),
                                       result=f"proxy read: {e}")
                return MOSDOpReply(tid=msg.tid, retval=rep.retval,
                                   epoch=self.my_epoch(), data=rep.data,
                                   result=rep.result)
            rc = self._tier_promote(pg, pool, acting, base_id, oid,
                                    mark_clean=True)
            if rc == 1:
                return retry()  # raced a write: re-evaluate the staging
            if rc == -2:
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found")
            if rc != 0:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"promote failed ({rc})")
            return None  # promoted: serve locally

        # mutations (writeback; readproxy promotes writes too)
        if msg.op == "delete":
            if not present or whiteout:
                # nothing cached (or already whited out): existence is
                # decided by the base copy
                if whiteout:
                    return MOSDOpReply(tid=msg.tid, retval=-2,
                                       epoch=self.my_epoch(),
                                       result="not found (whiteout)")
                try:
                    st = self._tier_client_op(base_id, oid, "stat")
                except OSError as e:
                    return MOSDOpReply(tid=msg.tid, retval=-11,
                                       epoch=self.my_epoch(),
                                       result=f"tier stat: {e}")
                if st.retval != 0:
                    return MOSDOpReply(tid=msg.tid, retval=-2,
                                       epoch=self.my_epoch(),
                                       result="not found")
            # install the whiteout stub: empty object + markers; the
            # agent propagates the delete to the base and retires it
            wrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="write_full", data=pack_data(b""),
                epoch=self.my_epoch(), reqid=getattr(msg, "reqid", None),
            ))
            if wrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=wrep.retval,
                                   epoch=self.my_epoch(), result=wrep.result)
            # the stub must shed the pre-delete user state THROUGH THE
            # REPLICATED paths (advisor r4, medium): a primary-local wipe
            # leaves replicas carrying stale xattrs/omap that resurrect
            # after failover, and a delete-then-recreate must never
            # resurrect pre-delete attrs into a later flush
            try:
                stale = {
                    n[2:]: None
                    for n in self.store.getattrs(cid, oid)
                    if n.startswith("u_") and not n[2:].startswith("tier.")
                }
            except (NotFound, KeyError):
                stale = {}
            if stale:
                xrep = self._xattr_op(pg, acting, 0, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="setxattr", data=stale, epoch=self.my_epoch(),
                ))
                if xrep.retval != 0:
                    return MOSDOpReply(tid=msg.tid, retval=xrep.retval,
                                       epoch=self.my_epoch(),
                                       result=xrep.result)
            orep = self._omap_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="omap_clear", data={}, epoch=self.my_epoch(),
            ))
            if orep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=orep.retval,
                                   epoch=self.my_epoch(), result=orep.result)
            mrep = self._tier_mark(pg, acting, oid, "whiteout", True)
            if mrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=mrep.retval,
                                   epoch=self.my_epoch(), result=mrep.result)
            self._tier_mark(pg, acting, oid, "clean", False)
            return MOSDOpReply(tid=msg.tid, retval=0,
                               epoch=self.my_epoch(), result={})

        if whiteout:
            # write onto a deleted object: never resurrect base bytes —
            # clear the markers and start from the empty stub.  The clear
            # must be DURABLE before the data op: a stale whiteout
            # surviving primary failover would later flush as a delete,
            # destroying the acknowledged write
            mrep = self._tier_mark(pg, acting, oid, "whiteout", False)
            if mrep.retval != 0:
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result="whiteout clear not durable")
            return None
        if present:
            # the clean-marker clear now rides the mutation's OWN
            # transaction (_tier_autoclean in the write_full / omap /
            # xattr / exec paths), atomically under the same pg.lock —
            # a separate staging clear here raced the flush's clean-mark
            # (advisor r4, medium: flush could label the object clean
            # AFTER this check but BEFORE the data op landed)
            return None
        # absent: partial mutations need the base content staged first;
        # full overwrites don't (reference: proxy/promote decision).  A
        # base miss (rc == -2) just falls through: the normal path gives
        # xattr ops their -2 and creates fresh objects for write/omap,
        # matching un-tiered pool semantics.
        if msg.op not in ("write_full",):
            rc = self._tier_promote(pg, pool, acting, base_id, oid,
                                    mark_clean=False)
            if rc == 1:
                return retry()  # raced a write: re-evaluate the staging
            if rc not in (0, -2):
                return MOSDOpReply(tid=msg.tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"promote failed ({rc})")
        return None

    def _tier_promote(self, pg, pool, acting, base_id: int, oid: str,
                      mark_clean: bool) -> int:
        """Copy oid (data + user xattrs + omap) from the base pool into
        this cache PG (reference: PrimaryLogPG::promote_object).  Returns
        0, -2 (no base object), 1 (ABORTED: the object appeared locally
        while we read the base copy — the caller re-evaluates its staging
        decision), or a negative errno.

        Race contract (advisor r4, high): the base-pool reads run
        lock-free, but the local existence re-check and the staging
        writes run under pg.lock — a client write that staged fresh data
        concurrently either lands before our locked section (we see it
        and abort: promoting would overwrite acknowledged new data with
        stale base content) or serializes after it (its own transaction
        clears the clean marker we may set)."""
        try:
            rep = self._tier_client_op(base_id, oid, "read")
            if rep.retval == -2:
                return -2
            if rep.retval != 0:
                return rep.retval or -5
            xrep = self._tier_client_op(base_id, oid, "getxattrs")
            xattrs = dict(xrep.result or {}) if xrep.retval == 0 else {}
            orep = self._tier_client_op(base_id, oid, "omap_get")
            kv = dict((orep.result or {}).get("kv") or {}) \
                if orep.retval == 0 else {}
        except OSError:
            return -11
        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            if self.store.exists(cid, oid):
                return 1  # raced a write: fresh data already staged
            wrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="write_full", data=rep.data, epoch=self.my_epoch(),
            ))
            if wrep.retval != 0:
                return wrep.retval or -5
            if xattrs:
                self._xattr_op(pg, acting, 0, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="setxattr", data=xattrs, epoch=self.my_epoch(),
                ))
            if kv:
                self._omap_op(pg, pool, acting, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="omap_set", data={"keys": kv}, epoch=self.my_epoch(),
                ))
            if mark_clean:
                self._tier_mark(pg, acting, oid, "clean", True)
        self.logger.inc("tier_promote")
        return 0

    def _tier_flush_object(self, pg, pool, acting, oid: str,
                           tid: int) -> MOSDOpReply:
        """Flush one cache object to the base pool (reference:
        PrimaryLogPG::start_flush).  Whiteouts propagate the delete and
        retire the stub; dirty objects copy content and gain the clean
        marker — guarded by a version recheck so a write racing the
        flush re-dirties instead of being mislabeled clean."""
        base_id = pool.tier_of
        cid = self._cid(pg.pgid, 0)
        if not self.store.exists(cid, oid):
            return MOSDOpReply(tid=tid, retval=-2, epoch=self.my_epoch(),
                               result="not found")
        if self._tier_flag(pg, oid, "whiteout"):
            try:
                drep = self._tier_client_op(base_id, oid, "delete")
            except OSError as e:
                return MOSDOpReply(tid=tid, retval=-11,
                                   epoch=self.my_epoch(),
                                   result=f"flush delete: {e}")
            if drep.retval not in (0, -2):
                return MOSDOpReply(tid=tid, retval=drep.retval,
                                   epoch=self.my_epoch(), result=drep.result)
            # retire the stub under pg.lock, re-checking the marker: a
            # client write racing this flush clears the whiteout and
            # stages fresh data in the stub — deleting it then would lose
            # an acknowledged write (the re-dirtied object simply flushes
            # again on the next pass, recreating the base copy)
            with pg.lock:
                if not self._tier_flag(pg, oid, "whiteout"):
                    return MOSDOpReply(
                        tid=tid, retval=0, epoch=self.my_epoch(),
                        result={"flushed": "raced a rewrite; kept"})
                rrep = self._replicated_op(pg, pool, acting, MOSDOp(
                    tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                    op="delete", epoch=self.my_epoch(),
                ))
            return MOSDOpReply(tid=tid, retval=rrep.retval,
                               epoch=self.my_epoch(),
                               result={"flushed": "whiteout"})
        if self._tier_flag(pg, oid, "clean"):
            return MOSDOpReply(tid=tid, retval=0, epoch=self.my_epoch(),
                               result={"flushed": "already clean"})
        try:
            ver_before = self.store.getattr(cid, oid, "ver")
        except (NotFound, KeyError):
            ver_before = None
        data = bytes(self.store.read(cid, oid))
        xattrs = {
            n[2:]: pack_data(v)
            for n, v in self.store.getattrs(cid, oid).items()
            if n.startswith("u_") and not n[2:].startswith("tier.")
        }
        kv = self.store.omap_get(cid, oid)
        try:
            wrep = self._tier_client_op(base_id, oid, "write_full",
                                        data=pack_data(data))
            if wrep.retval != 0:
                return MOSDOpReply(tid=tid, retval=wrep.retval,
                                   epoch=self.my_epoch(), result=wrep.result)
            if xattrs:
                self._tier_client_op(base_id, oid, "setxattr", data=xattrs)
            if kv:
                self._tier_client_op(
                    base_id, oid, "omap_set",
                    data={"keys": {k: pack_data(v) for k, v in kv.items()}},
                )
        except OSError as e:
            return MOSDOpReply(tid=tid, retval=-11, epoch=self.my_epoch(),
                               result=f"flush write: {e}")
        with pg.lock:
            try:
                ver_now = self.store.getattr(cid, oid, "ver")
            except (NotFound, KeyError):
                ver_now = None
            if ver_now == ver_before:
                self._tier_mark(pg, acting, oid, "clean", True)
        self.logger.inc("tier_flush")
        return MOSDOpReply(tid=tid, retval=0, epoch=self.my_epoch(),
                           result={"flushed": len(data)})

    def _tier_evict_object(self, pg, pool, acting, oid: str,
                           tid: int) -> MOSDOpReply:
        """Drop a CLEAN cache copy (reference: PrimaryLogPG::_delete_oid
        under agent_maybe_evict); -EBUSY for dirty/whiteout objects."""
        cid = self._cid(pg.pgid, 0)
        with pg.lock:
            if not self.store.exists(cid, oid):
                return MOSDOpReply(tid=tid, retval=-2,
                                   epoch=self.my_epoch(),
                                   result="not found")
            if (
                not self._tier_flag(pg, oid, "clean")
                or self._tier_flag(pg, oid, "whiteout")
            ):
                return MOSDOpReply(tid=tid, retval=-16,
                                   epoch=self.my_epoch(),
                                   result="dirty: flush first")
            rrep = self._replicated_op(pg, pool, acting, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="delete", epoch=self.my_epoch(),
            ))
        if rrep.retval != 0:
            return MOSDOpReply(tid=tid, retval=rrep.retval,
                               epoch=self.my_epoch(), result=rrep.result)
        self.logger.inc("tier_evict")
        return MOSDOpReply(tid=tid, retval=0,
                           epoch=self.my_epoch(), result={"evicted": oid})

    def _tier_agent_pass(self) -> None:
        """Background flush/evict over primary cache-pool PGs (reference:
        the TierAgent woken by agent_choose_mode).  Flushes every dirty
        object and whiteout; evicts clean objects while the pool is over
        target_max_objects (eviction order is name-sorted — the
        reference ranks by hit_set temperature, out of scope here)."""
        m = self.osdmap
        if m is None:
            return
        for pool in list(m.pools.values()):
            # readproxy pools flush too: their writes stage dirty in the
            # cache exactly like writeback (only reads are proxied)
            if pool.tier_of < 0 or pool.cache_mode == "none":
                continue
            for ps in range(pool.pg_num):
                acting, primary = self._acting(pool.pool_id, ps)
                if primary != self.id:
                    continue
                pg = self._pg(pool.pool_id, ps)
                if pg.activated_interval != pg.interval_start:
                    continue
                cid = self._cid(pg.pgid, 0)
                try:
                    oids = [
                        o for o in self.store.list_objects(cid)
                        if not o.startswith("_") and CLONE_SEP not in o
                    ]
                except (NotFound, KeyError):
                    continue
                live = []
                for oid in sorted(oids):
                    if self._tier_flag(pg, oid, "whiteout") or \
                            not self._tier_flag(pg, oid, "clean"):
                        try:
                            self._tier_flush_object(
                                pg, pool, acting, oid, self._next_tid()
                            )
                        except Exception as e:
                            self.cct.dout(
                                "osd", 5,
                                f"{self.whoami} tier flush {oid}: {e!r}")
                    if self.store.exists(cid, oid):
                        live.append(oid)
                target = pool.target_max_objects
                if target and len(live) > max(0, target // pool.pg_num):
                    for oid in live[max(0, target // pool.pg_num):]:
                        try:
                            self._tier_evict_object(
                                pg, pool, acting, oid, self._next_tid()
                            )
                        except Exception as e:
                            # eviction is opportunistic (the next agent
                            # pass retries), but never silent
                            self.cct.dout(
                                "osd", 5,
                                f"{self.whoami} tier evict {oid}: {e!r}")

