"""Bounded per-PG op log — the data plane's checkpoint/resume mechanism
(reference: src/osd/PGLog.{h,cc} + pg_log_entry_t in osd_types.h;
SURVEY.md §5.4 "an OSD returning after a short outage replays the delta
instead of full copy").

Simplifications vs the reference, by design:
- versions are a single monotonically increasing integer per PG (the
  reference's eversion_t (epoch, version) — epochs matter there because
  primaries diverge; here the primary serializes all writes and peering
  truncates stragglers, so a scalar version is sufficient and the
  divergent-entry rewind machinery collapses into `entries_since`).
- entries record (version, op, oid); op is "modify", "delete", "attr"
  (an xattr-only mutation: recovered exactly like a modify, but it does
  NOT move the object's data-generation floor — chunk bytes are
  untouched, so no chunk stamp will ever carry its version), or "clean"
  (a data-less version marker recovery uses to seal a peer at the
  primary's version) — enough to reconstruct a missing-object set, which
  is all recovery needs.

Persistence: the log rides in the same ObjectStore transaction as the data
write (omap of the PG meta object), exactly how the reference keeps log and
data atomic.
"""
from __future__ import annotations

from dataclasses import dataclass

DEFAULT_LOG_LIMIT = 500  # reference: osd_min_pg_log_entries ballpark


@dataclass(frozen=True)
class LogEntry:
    version: int
    op: str  # "modify" | "delete" | "clean"
    oid: str
    # client reqid of the mutation, if any (reference: pg_log_entry_t's
    # reqid / pg_log_dup_t): because it rides IN the replicated+persisted
    # log entry, dup detection survives primary restarts and acting-set
    # changes — a new primary's delta-recovered log still answers resends
    reqid: str | None = None

    def to_list(self) -> list:
        if self.reqid is None:
            return [self.version, self.op, self.oid]
        return [self.version, self.op, self.oid, self.reqid]

    @classmethod
    def from_list(cls, v: list) -> "LogEntry":
        return cls(int(v[0]), str(v[1]), str(v[2]),
                   str(v[3]) if len(v) > 3 else None)


class PGLog:
    """In-memory form; persisted as omap keys by the owning PG."""

    def __init__(self, limit: int = DEFAULT_LOG_LIMIT):
        self.limit = limit
        self.entries: list[LogEntry] = []  # ascending version
        self.head = 0          # newest version (0 = empty PG)
        self.tail = 0          # version BEFORE the oldest retained entry
        # reqid -> version for the retained window (reference:
        # pg_log_dup_t set): dup detection against the replicated log
        self.reqids: dict[str, int] = {}
        # oid -> newest DATA-mutation version ever logged (reference:
        # the missing-set's need versions): the generation FLOOR readers
        # and rebuilders require — serving a chunk generation below it
        # would resurrect pre-write bytes whenever the current copies
        # are temporarily unreachable.  Kept across trims (floors stay
        # true); rebuilt from the retained window after a reload.
        self.obj_newest: dict[str, int] = {}

    def append(self, entry: LogEntry) -> list[LogEntry]:
        """Append and trim; returns entries trimmed off the tail."""
        assert entry.version > self.head, (entry, self.head)
        self.entries.append(entry)
        self.head = entry.version
        if entry.reqid is not None:
            self.reqids[entry.reqid] = entry.version
        if entry.op in ("modify", "delete"):
            # NOT "attr": xattr-only entries leave chunk bytes (and
            # stamps) alone, so they must not raise the data floor
            self.obj_newest[entry.oid] = entry.version
        trimmed: list[LogEntry] = []
        while len(self.entries) > self.limit:
            e = self.entries.pop(0)
            trimmed.append(e)
            self.tail = e.version
            if e.reqid is not None and self.reqids.get(e.reqid) == e.version:
                self.reqids.pop(e.reqid, None)
        return trimmed

    def find_reqid(self, reqid: str) -> int | None:
        """Version at which a client op was applied, if it is in the
        retained log window (None = never seen or trimmed away)."""
        return self.reqids.get(reqid)

    def covers(self, version: int) -> bool:
        """Can a peer at `version` be delta-recovered from this log?"""
        return version >= self.tail

    def reset_to(self, version: int) -> None:
        """Empty the log window at `version` (head = tail = version): the
        state after a full backfill, where nothing below `version` can be
        vouched for entry-by-entry (reference: pg_log rewind/reset on
        backfill completion keeps covers() honest)."""
        self.entries = []
        self.head = self.tail = version
        self.reqids = {}
        # obj_newest survives: the floors reflect real history

    def entries_since(self, version: int) -> list[LogEntry]:
        return [e for e in self.entries if e.version > version]

    def missing_since(self, version: int) -> tuple[dict[str, int], set[str]]:
        """(oid -> newest version to recover, oids deleted) for a peer at
        `version` (reference: pg_missing_t built from log divergence)."""
        newest: dict[str, int] = {}
        deleted: set[str] = set()
        for e in self.entries_since(version):
            if e.op == "clean":
                continue  # version marker, no object behind it
            if e.op == "delete":
                deleted.add(e.oid)
                newest.pop(e.oid, None)
            else:
                deleted.discard(e.oid)
                newest[e.oid] = e.version
        return newest, deleted

    # -- persistence -------------------------------------------------------
    @staticmethod
    def omap_key(version: int) -> str:
        return f"log.{version:016d}"

    @classmethod
    def load(cls, pairs: dict[str, bytes], head: int, tail: int,
             limit: int = DEFAULT_LOG_LIMIT) -> "PGLog":
        import json

        log = cls(limit)
        log.head, log.tail = head, tail
        for k in sorted(pairs):
            if k.startswith("log."):
                e = LogEntry.from_list(json.loads(pairs[k]))
                # stale keys below the window (left behind by a reset_to
                # seal) must not resurrect into the live log
                if tail < e.version <= head:
                    log.entries.append(e)
                    if e.reqid is not None:
                        log.reqids[e.reqid] = e.version
                    if e.op in ("modify", "delete"):
                        log.obj_newest[e.oid] = max(
                            log.obj_newest.get(e.oid, 0), e.version)
        return log
