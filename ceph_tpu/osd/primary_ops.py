"""Client-op execution on the primary (reference: src/osd/PrimaryLogPG.cc do_op/do_osd_ops) plus pool-snapshot clone-on-write (make_writeable).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations


import threading
import time


from ..common.tracer import TRACER, TraceCtx, set_op_trace, trace_now
from ..store.object_store import NotFound
from .messages import (
    MECSubOpRead,
    MOSDOp,
    MOSDOpReply,
    pack_data,
    unpack_data,
)
from ..osd.osdmap import PG_POOL_ERASURE, object_ps
from .pg import CLONE_SEP, MUTATING_OPS


class PrimaryOpsMixin:
    # -- client ops (primary) ---------------------------------------------
    def _handle_client_op(self, conn, msg: MOSDOp) -> None:
        t0 = time.perf_counter()
        self.logger.inc("op")
        wr_bytes = 0
        if msg.op == "write_full":
            self.logger.inc("op_w")
            wr_bytes = len(msg.data or "") * 3 // 4
            self.logger.inc("op_w_bytes", wr_bytes)
        elif msg.op == "read":
            self.logger.inc("op_r")
        tracked = self.op_tracker.create(
            f"osd_op({msg.op} {msg.pool}.{msg.oid} tid={msg.tid})"
        )
        # cephmeter: (client entity, pool) stamp — msg.src is the
        # messenger-framed entity name the Objecter sends under.  These
        # labels ARE the future mClock tags; the accounting table and
        # the write batcher (through the op-trace state) attribute
        # per-stage latency to them (docs/observability.md)
        client = getattr(msg, "src", None) or "client._unknown_"
        tracked.trace_id = getattr(msg, "trace_id", None)
        # cephtrace: adopt the client's context (one attribute check
        # when tracing is off).  The osd_op span parents every stage
        # span below; the thread-local op-trace state is how the write
        # batcher / encode / sub-op layers find it without threading a
        # ctx through every signature.
        osd_span = None
        if TRACER.enabled and getattr(msg, "trace_id", None) is not None:
            osd_span = TRACER.begin(
                TraceCtx(msg.trace_id, msg.parent_span), "osd_op",
                entity=self.whoami, op=msg.op, oid=msg.oid, tid=msg.tid,
            )
            rx = getattr(msg, "_rx_ts", None)
            if osd_span is not None and rx is not None:
                # mClock dispatch-queue wait (arrival -> execution)
                TRACER.record(osd_span.ctx(), "dispatch_queue",
                              entity=self.whoami, t0=rx, t1=osd_span.t0)
        set_op_trace({
            "ctx": osd_span.ctx() if osd_span is not None else None,
            "tracked": tracked,
            "acct": ((self.io_acct, client, msg.pool)
                     if self.io_acct is not None else None),
        })
        reply = None
        try:
            tracked.mark_event("started")
            reply = self._execute_client_op(msg)
        except Exception as e:  # never leave the client hanging
            tracked.mark_event(f"failed: {e!r}")
            self.cct.dout("osd", 0, f"{self.whoami} op failed: {e!r}")
            reply = MOSDOpReply(
                tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                result=f"internal error: {e}",
            )
        finally:
            tracked.finish()
            set_op_trace(None)
            TRACER.end(osd_span,
                       retval=reply.retval if reply is not None else None)
            if TRACER.enabled and tracked.trace_id is not None:
                self._maybe_tail_promote(tracked)
        if msg.op == "read" and reply.retval == 0 and reply.data:
            self.logger.inc("op_r_bytes", len(reply.data) * 3 // 4)
        if self.io_acct is not None:
            nbytes = wr_bytes
            if msg.op == "read" and reply.retval == 0 and reply.data:
                nbytes = len(reply.data) * 3 // 4
            self.io_acct.record_op(client, msg.pool, msg.op,
                                   nbytes=nbytes, e2e=tracked.duration())
        self.logger.tinc("op_latency", time.perf_counter() - t0)
        try:
            conn.send_message(reply)
        except (OSError, ConnectionError):
            pass

    def _maybe_tail_promote(self, tracked) -> None:
        """cephmeter tail sampling, primary side: an op that crossed
        osd_op_complaint_time or trace_tail_latency_ms promotes its
        provisionally buffered trace into the real buffer — even when
        the head coin flip said no (trace_sampling_rate=0).  Runs after
        the osd_op span ended, so the whole OSD-side subtree (and the
        replicas' commit spans, already ended before the acks were
        collected) moves together."""
        dur = tracked.duration()
        complaint = self.op_tracker.complaint_time
        tail_ms = float(self.cct.conf.get("trace_tail_latency_ms"))
        if complaint > 0 and dur > complaint:
            TRACER.promote(tracked.trace_id, reason="osd_complaint")
        elif tail_ms > 0 and dur * 1e3 >= tail_ms:
            TRACER.promote(tracked.trace_id, reason="osd_tail")

    def _execute_client_op(self, msg: MOSDOp) -> MOSDOpReply:
        # the client targeted with a NEWER map than ours: wait for it
        # before deciding anything (reference: OSD::require_same_or_newer_map
        # waiting_for_map) — answering from the stale map would yield
        # false 'no such pool' / wrong-primary verdicts
        if msg.epoch and msg.epoch > self.my_epoch():
            deadline = time.monotonic() + 10.0
            while (
                msg.epoch > self.my_epoch()
                and time.monotonic() < deadline
                and not self._stop.is_set()
            ):
                time.sleep(0.05)
            if msg.epoch > self.my_epoch():
                # still behind: NACK retryably — answering from a map the
                # client provably outdates would yield FINAL wrong results
                # ('no such pool', wrong primary)
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result="waiting for newer osdmap",
                )
        m = self.osdmap
        pool = m.pools.get(msg.pool) if m else None
        if m is None or pool is None:
            return MOSDOpReply(tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                               result="no such pool")
        if (
            msg.op in ("list", "scrub", "scrub-noprepair")
            and msg.oid
            and msg.oid.startswith(":pg:")
        ):
            ps = int(msg.oid[4:])  # pg-targeted op (tools/librados)
        elif getattr(msg, "ps", None) is not None:
            # explicit placement seed: the split migrator addressing an
            # object still housed in its pre-split PG
            ps = int(msg.ps)
        else:
            ps = object_ps(msg.oid, pool.pg_num) if msg.oid else 0
        if msg.op in ("scrub", "scrub-noprepair"):
            try:
                result = self.scrub_pg(msg.pool, ps,
                                       repair=msg.op == "scrub")
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(), result=result)
            except RuntimeError:
                pass  # not primary: fall through to the -116 NACK below
        acting, primary = self._acting(msg.pool, ps)
        if primary != self.id:
            # client raced a map change (Objecter resend rule)
            return MOSDOpReply(
                tid=msg.tid, retval=-116, epoch=self.my_epoch(),
                result={"primary": primary},
            )
        pg = self._pg(msg.pool, ps)
        if pg.activated_interval != pg.interval_start:
            # not yet peered for the current interval: refuse retryably
            # and peer NOW (reference: ops wait on PG activation)
            self._recovery_wakeup.set()
            return MOSDOpReply(
                tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                result="peering: pg not active in this interval",
            )
        # dup detection + in-flight serialization (reference: pg_log dup
        # entries + PrimaryLogPG::check_in_progress_op): a resend of a
        # completed mutation is answered without re-executing — from the
        # reply cache, or (surviving primary changes) from the reqid the
        # REPLICATED log entry carries; a resend racing the still-running
        # original waits for it instead of double-executing
        reqid = getattr(msg, "reqid", None)
        if reqid is not None and msg.op in MUTATING_OPS:
            rep = self._check_dup(pg, pool, acting, msg, reqid)
            if rep is not None:
                return rep
            while True:
                guard = threading.Event()
                prior = pg.inflight.setdefault(reqid, guard)
                if prior is guard:
                    # we own the slot — but the original may have
                    # COMPLETED between our _check_dup miss and now
                    # (check-then-act): re-check before executing
                    rep = self._check_dup(pg, pool, acting, msg, reqid)
                    if rep is not None:
                        pg.inflight.pop(reqid, None)
                        guard.set()
                        return rep
                    break
                if not prior.wait(60.0):
                    # original STILL running (e.g. a long degraded
                    # splice): executing now would double-apply — refuse
                    # retryably and let the next resend re-check
                    return MOSDOpReply(
                        tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                        result="op with same reqid still in flight",
                    )
                rep = self._check_dup(pg, pool, acting, msg, reqid)
                if rep is not None:
                    return rep
                # the original died before logging anything — loop back
                # to CONTEND for the slot (setdefault): two waiters must
                # not both install themselves and double-execute
            try:
                return self._execute_routed_op(pg, pool, acting, ps, msg)
            finally:
                pg.inflight.pop(reqid, None)
                guard.set()
        return self._execute_routed_op(pg, pool, acting, ps, msg)

    def _check_dup(self, pg, pool, acting, msg, reqid) -> MOSDOpReply | None:
        """Reply for an already-seen reqid, or None to execute."""
        hit = pg.reqid_cache.get(reqid)
        if hit is not None and hit[0] == "forked":
            # executed here in a DEAD interval: the fork is invisible to
            # the real history; re-execute (a still-stale primary gets
            # deposed again until its map catches up)
            return None
        if hit is None:
            v = pg.log.find_reqid(reqid)
            if v is not None:
                hit = ("applied", v)
        if hit is None:
            return None
        if hit[0] == "done":
            return MOSDOpReply(tid=msg.tid, retval=hit[1],
                               epoch=self.my_epoch(), result=hit[2])
        # ("applied", v): the op mutated state exactly once but was
        # under-acked (< min_size commits) at the time.  Never re-execute.
        # Success is reported only when the write has ACTUALLY reached
        # min_size shards — counted from the per-object version stamps,
        # not mere reachability (reachable-but-unrecovered shards don't
        # hold the data yet).  Deletes are idempotent at the log level:
        # applied = done.
        if msg.op == "delete":
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "dup": True})
        holding = 0
        is_ec = pool.type == PG_POOL_ERASURE
        for shard, osd in enumerate(acting):
            if osd < 0:
                continue
            # replicated pools keep every replica in the shard-0
            # collection; only EC pools have per-shard collections
            store_shard = shard if is_ec else 0
            if osd == self.id:
                v = self._stored_ver(self._cid(pg.pgid, store_shard),
                                     msg.oid)
                if v is not None and v >= hit[1]:
                    holding += 1
                continue
            if not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(MECSubOpRead(
                    tid=tid, pgid=pg.pgid, oid=msg.oid, shard=store_shard,
                    offsets=[], epoch=self.my_epoch(),
                ))
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.retval != 0:
                continue
            v = getattr(rep, "ver", None)
            if v is not None and v >= hit[1]:
                holding += 1
        if holding >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "dup": True})
        # the op is durably logged but under-replicated: recovery is the
        # only path to an ack, so kick it rather than wait for the tick
        self._recovery_wakeup.set()
        return MOSDOpReply(
            tid=msg.tid, retval=-11, epoch=self.my_epoch(),
            result=f"applied at v{hit[1]}; {holding} shards hold it "
                   f"< min_size {pool.min_size}",
        )

    def _execute_routed_op(self, pg, pool, acting, ps, msg) -> MOSDOpReply:
        quota_pools = ["full_quota" in getattr(pool, "flags", ())]
        if pool.tier_of >= 0 and self.osdmap is not None:
            # a CACHE pool fronts its base: client writes redirected
            # here must honor the BASE pool's quota or the overlay
            # becomes a quota bypass (review r5)
            base = self.osdmap.pools.get(pool.tier_of)
            quota_pools.append(
                base is not None
                and "full_quota" in getattr(base, "flags", ())
            )
        if (
            any(quota_pools)
            and msg.op in MUTATING_OPS
            and msg.op != "delete"  # deletes free space, always allowed
            # internal tier traffic (flush/promote staging) moves bytes
            # BETWEEN the tiers, bounded by the cache size — refusing it
            # would wedge dirty objects in the cache forever
            and not str(getattr(msg, "reqid", "") or "").startswith("tier.")
        ):
            # reference: PrimaryLogPG refuses writes on FLAG_FULL_QUOTA
            # pools with -EDQUOT; the mgr's quota loop set the flag
            return MOSDOpReply(
                tid=msg.tid, retval=-122, epoch=self.my_epoch(),
                result=f"pool {pool.name!r} quota exceeded (EDQUOT)",
            )
        if msg.op == "write" and int(msg.off or 0) < 0:
            # reference: negative offsets are -EINVAL; Python slicing
            # would otherwise silently splice into the object's tail
            return MOSDOpReply(tid=msg.tid, retval=-22,
                               epoch=self.my_epoch(),
                               result="negative write offset")
        # cache-tier front-end: a PG in a cache pool stages/proxies/
        # whiteouts before normal execution (reference: PrimaryLogPG::
        # maybe_handle_cache_detail runs before do_op proper)
        if pool.tier_of >= 0 and pool.cache_mode != "none":
            rep = self._cache_tier_op(pg, pool, acting, ps, msg)
            if rep is not None:
                return self._record_reqid(pg, msg, rep)
        # pool snapshots (reference: make_writeable's clone-on-write +
        # SnapSet resolution in PrimaryLogPG)
        # clone against the newest LIVE snap (snap_seq never resets, and
        # cloning for snaps that no longer exist would leak un-trimmable
        # copies on every first write); the client's snap context covers
        # the window where this map lags a fresh mksnap
        live_max = max(pool.snaps, default=0)
        snap_seq = max(live_max, int(getattr(msg, "snap_seq", 0) or 0))
        if (
            msg.op in ("write_full", "write", "append", "delete")
            and snap_seq
            and msg.oid
            and CLONE_SEP not in msg.oid
            and getattr(msg, "ps", None) is None
            # explicit-ps ops are internal machinery (split migration,
            # trim), not client mutations: the split's old-PG delete must
            # not mint a stranded clone — the head's bytes live on,
            # unchanged, in the post-split PG
        ):
            try:
                head_existed = self._maybe_clone(pg, pool, msg.oid, snap_seq)
            except Exception as e:
                # clone failures are overwhelmingly transient races (a
                # map change mid-op re-targeting the internal clone
                # write, a peer mid-recovery): refuse RETRYABLY so the
                # client resends to the current primary — a fatal -EIO
                # here would fail a write that the next attempt performs
                # cleanly
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"snap clone failed: {e}",
                )
            if msg.op in ("write_full", "write", "append") and not head_existed:
                rep = (
                    self._ec_op(pg, pool, acting, msg)
                    if pool.type == PG_POOL_ERASURE
                    else self._replicated_op(pg, pool, acting, msg)
                )
                if rep.retval == 0:
                    try:
                        self._mark_born(pg, pool, msg.oid, snap_seq)
                    except Exception as e:
                        # same contract as _set_born: a lost born marker
                        # would surface this object in snap views older
                        # than its creation, so fail the write instead
                        return MOSDOpReply(
                            tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                            result=f"snapborn mark failed: {e}",
                        )
                return self._record_reqid(pg, msg, rep)
        if (
            msg.op == "read"
            and getattr(msg, "snapid", None)
            and CLONE_SEP not in msg.oid
        ):
            clone_oid = self._resolve_snap_read(
                pg, pool, acting, msg.oid, int(msg.snapid)
            )
            if clone_oid is None:
                # object was created after the snapshot
                return MOSDOpReply(
                    tid=msg.tid, retval=-2, epoch=self.my_epoch(),
                    result="did not exist at snap",
                )
            if clone_oid != msg.oid:
                msg = MOSDOp(
                    tid=msg.tid, pool=msg.pool, oid=clone_oid, op="read",
                    epoch=msg.epoch, off=msg.off, length=msg.length,
                    ps=ps,
                )
        if pool.type == PG_POOL_ERASURE:
            rep = self._ec_op(pg, pool, acting, msg)
        else:
            rep = self._replicated_op(pg, pool, acting, msg)
        return self._record_reqid(pg, msg, rep)

    def _collect_subop_acks(self, tids: dict, acting=None):
        """(acked_remote, deposed, failed_osds) over a tid->shard map.
        `deposed` = some peer answered -116: it is in a NEWER interval
        than the epoch we stamped — we may have been deposed mid-op."""
        acked = 0
        deposed = False
        failed: list[int] = []
        for tid, shard in tids.items():
            rep = self._wait_reply(tid)
            if rep is not None and rep.retval == 0:
                acked += 1
            elif rep is not None and rep.retval == -116:
                deposed = True
            elif acting is not None:
                failed.append(acting[shard])
        return acked, deposed, failed

    def _record_reqid(self, pg, msg, rep: MOSDOpReply) -> MOSDOpReply:
        """Remember a completed mutation's outcome for dup detection.
        Successes cache the full reply; an UNDER-ACKED mutation (applied
        and logged, but < min_size commits, reported -11) caches the
        applied-at version so the resend re-evaluates availability
        instead of re-executing — re-running an append/RMW would
        double-apply.  Plain refusals (gate -11, -ESTALE) that mutated
        nothing cache nothing and re-execute freely."""
        reqid = getattr(msg, "reqid", None)
        if reqid is None or msg.op not in MUTATING_OPS:
            return rep
        if rep.retval == 0:
            pg.reqid_cache[reqid] = ("done", rep.retval, rep.result)
        elif (
            rep.retval == -116
            and isinstance(rep.result, dict)
            and rep.result.get("deposed")
        ):
            # the op executed on a DEPOSED primary: its local log entry
            # is a fork in a dead interval — the marker stops this OSD's
            # own log from answering the resend as an "applied" dup
            pg.reqid_cache[reqid] = ("forked",)
        elif (
            rep.retval == -11
            and isinstance(rep.result, dict)
            and "applied" in rep.result
        ):
            pg.reqid_cache[reqid] = ("applied", rep.result["applied"])
            self._recovery_wakeup.set()  # under-acked: converge now
        else:
            return rep
        while len(pg.reqid_cache) > 1024:
            pg.reqid_cache.popitem(last=False)
        return rep

    # -- pool snapshots ----------------------------------------------------
    def _clone_oid(self, oid: str, snapid: int) -> str:
        return f"{oid}{CLONE_SEP}{snapid:08d}"

    def _maybe_clone(self, pg, pool, oid: str, snap_seq: int) -> None:
        """Clone-on-first-write-after-snap: preserve the head's bytes as
        clone `snap_seq` before an overwrite/delete mutates it.  The clone
        is a full normal object in the SAME PG (explicit ps), so
        replication/EC encoding, recovery, and scrub all cover it.

        The stat->read->write sequence is serialized under _clone_mutex:
        two concurrent writers racing it could otherwise both miss the
        stat and the later one would capture POST-snap bytes as the
        clone, corrupting the snapshot view."""
        with self._clone_mutex:
            return self._maybe_clone_locked(pg, pool, oid, snap_seq)

    def _maybe_clone_locked(self, pg, pool, oid: str, snap_seq: int) -> bool:
        """Returns True when the head EXISTED (clone made or already
        present); False = brand-new object this write creates."""
        clone = self._clone_oid(oid, snap_seq)
        e = self.my_epoch()
        st = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=clone, op="stat",
            epoch=e, ps=pg.ps,
        ))
        if st.retval == 0:
            # this snap generation already preserved; a retried clone
            # whose marker write was interrupted gets repaired here (the
            # marker is what keeps born-after objects out of older views)
            if self._born_of(pg, pool, clone) == 0:
                born = self._born_of(pg, pool, oid)
                if born:
                    self._set_born(pg, pool, clone, born)
            return True
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid, op="read",
            epoch=e, ps=pg.ps, off=0, length=0,
        ))
        if r.retval != 0:
            return False  # no head: nothing to preserve
        w = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=clone,
            op="write_full", data=r.data, epoch=e, ps=pg.ps,
        ))
        if w.retval != 0:
            raise RuntimeError(f"clone write: {w.result}")
        born = self._born_of(pg, pool, oid)
        if born:
            self._set_born(pg, pool, clone, born)
        return True

    def _set_born(self, pg, pool, oid: str, born: int) -> None:
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid,
            op="setxattr", epoch=self.my_epoch(), ps=pg.ps,
            data={"_snapborn": pack_data(str(born).encode())},
        ))
        if r.retval != 0:
            # fail the client write rather than leave a clone that would
            # surface a born-after object in older snap views
            raise RuntimeError(f"clone born-marker write: {r.result}")

    def _born_of(self, pg, pool, oid: str) -> int:
        """Snap generation an object (head or clone) was created in; 0 =
        pre-snapshot or unmarked."""
        xr = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pool.pool_id, oid=oid,
            op="getxattrs", epoch=self.my_epoch(), ps=pg.ps,
        ))
        if xr.retval == 0 and isinstance(xr.result, dict):
            born = xr.result.get("_snapborn")
            if born is not None:
                try:
                    return int(unpack_data(born).decode())
                except (ValueError, AttributeError):
                    pass
        return 0

    def _mark_born(self, pg, pool, oid: str, snap_seq: int) -> None:
        """Stamp a newly created object with the snap generation it was
        born in, so snapshot reads older than its creation return ENOENT
        instead of the head (reference: SnapSet knows object existence
        per snap).  Rides the replicated user-xattr path under a
        reserved '_'-name the client surface filters out.  Raises on
        persistent failure (after one retry) — the caller fails the
        client write, matching _set_born's contract."""
        r = None
        for _ in range(2):
            r = self._execute_client_op(MOSDOp(
                tid=self._next_tid(), pool=pool.pool_id, oid=oid,
                op="setxattr", epoch=self.my_epoch(), ps=pg.ps,
                data={"_snapborn": pack_data(str(snap_seq).encode())},
            ))
            if r.retval == 0:
                return
        raise RuntimeError(f"snapborn marker write: {r.result}")

    def _primary_cid(self, pg, pool, acting) -> str:
        shard = acting.index(self.id) if pool.type == PG_POOL_ERASURE else 0
        return self._cid(pg.pgid, shard)

    def _resolve_snap_read(
        self, pg, pool, acting, oid: str, snapid: int
    ) -> str:
        """Oldest clone at-or-after `snapid` serves the snapshot view; no
        such clone means the head hasn't changed since (or never existed).
        reference: SnapSet::get_clone_bytes / find_object lookup."""
        prefix = oid + CLONE_SEP
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return oid
        ids = sorted(
            int(n[len(prefix):]) for n in names if n.startswith(prefix)
        )
        for c in ids:
            if c >= snapid:
                clone = self._clone_oid(oid, c)
                # the clone inherits its head's born marker: a clone made
                # AFTER a post-snap creation must not make the object
                # appear in older snap views
                if self._born_of(pg, pool, clone) >= snapid:
                    return None
                return clone
        # no clone: the head serves the snap view — unless the object was
        # born after the snapshot (its _snapborn generation >= snapid)
        if self._born_of(pg, pool, oid) >= snapid:
            return None
        return oid

    def _snaptrim_pass(self) -> None:
        """Remove clones no live snap needs (reference: the snap-trim
        queue PrimaryLogPG works through after a snap is deleted, fed by
        SnapMapper).  A clone c of a head covers snaps in (prev_clone, c];
        with none of those alive it is garbage."""
        m = self.osdmap
        if m is None:
            return
        for pgid, pg in list(self.pgs.items()):
            if self._stop.is_set():
                return
            pool = m.pools.get(pg.pool_id)
            if pool is None:
                continue
            live_key = tuple(sorted(pool.snaps))
            if pg.snap_trimmed == live_key:
                continue
            acting, primary = self._acting(pg.pool_id, pg.ps)
            if primary != self.id or self.id not in acting:
                continue
            try:
                self._snaptrim_pg(pg, pool, acting, live_key)
                pg.snap_trimmed = live_key
            except Exception as e:
                self.cct.dout(
                    "osd", 1, f"{self.whoami} snaptrim {pgid}: {e!r}"
                )

    def _snaptrim_pg(self, pg, pool, acting, live_key) -> None:
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return
        by_head: dict[str, list[int]] = {}
        for n in names:
            if CLONE_SEP in n:
                head, _, suffix = n.partition(CLONE_SEP)
                by_head.setdefault(head, []).append(int(suffix))
        live = sorted(live_key)
        snap_seq = max([pool.snap_seq, *live_key]) if live_key else pool.snap_seq
        for head, ids in by_head.items():
            ids.sort()
            prev = 0
            for c in ids:
                if c > snap_seq:
                    # a generation this map hasn't seen yet (clone minted
                    # from a newer client's snap context right after a
                    # mksnap): deleting it would destroy the new snapshot
                    prev = c
                    continue
                needed = any(prev < s <= c for s in live)
                prev = c
                if needed:
                    continue
                d = self._execute_client_op(MOSDOp(
                    tid=self._next_tid(), pool=pool.pool_id,
                    oid=self._clone_oid(head, c), op="delete",
                    epoch=self.my_epoch(), ps=pg.ps,
                ))
                if d.retval != 0:
                    raise RuntimeError(f"trim {head}@{c}: {d.result}")

