"""Primary-copy replication (reference: src/osd/ReplicatedBackend.cc).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations




from ..common.crc32c import crc32c
from ..common.tracer import TRACER, trace_now
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpWrite,
    MOSDOp,
    MOSDOpReply,
    pack_data,
    unpack_data,
)
from .pg import CLONE_SEP
from .pg_log import LogEntry


class ReplicatedBackendMixin:
    # .. replicated pool ...................................................
    def _replicated_op(self, pg, pool, acting, msg) -> MOSDOpReply:
        """Primary-copy replication (reference: ReplicatedBackend): full
        object bytes to every acting replica, same log machinery."""
        acting = [o for o in acting if o >= 0]
        my_shard = 0  # replicated: every replica stores the full object
        cid = self._cid(pg.pgid, 0)
        if msg.op in ("write_full", "write", "append", "delete"):
            # min_size gate, as on the EC path
            reachable = sum(
                1 for o in acting
                if o == self.id or self.osdmap.is_up(o)
            )
            if reachable < pool.min_size:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"{reachable} replicas reachable < "
                           f"min_size {pool.min_size}",
                )
        if msg.op in ("write", "append"):
            # ranged write / append: splice into the primary's copy (the
            # primary always holds the authoritative full object on a
            # replicated pool) and replicate the result full-object —
            # the reference ships op-level deltas; full-object keeps the
            # one replication path here while the EC pool carries the
            # real RMW machinery.  The read-splice-replicate sequence
            # runs under pg.lock (reentrant) so two concurrent appends
            # cannot both read the same old length and lose one update;
            # the rebuilt op KEEPS the reqid so the logged entry still
            # answers cross-primary resends.
            with pg.lock:
                new = unpack_data(msg.data) or b""
                try:
                    old = bytes(self.store.read(cid, msg.oid))
                except (NotFound, KeyError):
                    old = b""
                off = len(old) if msg.op == "append" else int(msg.off or 0)
                buf = bytearray(max(len(old), off + len(new)))
                buf[:len(old)] = old
                buf[off:off + len(new)] = new
                msg = MOSDOp(
                    tid=msg.tid, pool=msg.pool, oid=msg.oid,
                    op="write_full", data=pack_data(bytes(buf)),
                    epoch=msg.epoch, ps=msg.ps,
                    reqid=getattr(msg, "reqid", None),
                )
                return self._replicated_op(pg, pool, acting, msg)
        if msg.op == "write_full":
            data = unpack_data(msg.data) or b""
            # cache-tier pools: the clean-marker clear must ride THIS
            # mutation's transaction + sub-ops, not a separate staging
            # check (advisor r4 — the separate check races the flush's
            # clean-mark and an evict then drops the only copy)
            autoclean = self._tier_autoclean(pool, msg.oid)
            rmattrs = ["tier.clean"] if autoclean else None
            with pg.lock:
                version = pg.version + 1
                entry = LogEntry(version, "modify", msg.oid,
                                 reqid=getattr(msg, "reqid", None))
                tids = {}
                # subop span opens BEFORE the fan-out (see _ec_write)
                sub_span = TRACER.begin(self._op_trace_ctx(), "subop",
                                        entity=self.whoami) \
                    if TRACER.enabled else None
                t_sub0 = sub_span.t0 if sub_span is not None else trace_now()
                for osd in acting:
                    if osd == self.id or not self.osdmap.is_up(osd):
                        continue
                    tid = self._next_tid()
                    tids[tid] = osd
                    try:
                        self._conn_to_osd(osd).send_message(
                            MECSubOpWrite(
                                tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                                data=msg.data, crc=crc32c(data),
                                version=version,
                                entry=entry.to_list(),
                                epoch=self.my_epoch(), osize=len(data),
                                rmattrs=rmattrs,
                                trace_id=(sub_span.trace_id
                                          if sub_span is not None else None),
                                parent_span=(sub_span.span_id
                                             if sub_span is not None
                                             else None),
                            )
                        )
                    except (OSError, ConnectionError):
                        tids.pop(tid, None)
                t = Transaction()
                t.try_create_collection(cid)
                t.write(cid, msg.oid, 0, data)
                t.truncate(cid, msg.oid, len(data))
                # self-digest so scrub can tell at-rest rot on the primary
                # from divergence (replicas get theirs via sub-write)
                t.setattr(cid, msg.oid, "hinfo", str(crc32c(data)).encode())
                t.setattr(cid, msg.oid, "size", str(len(data)).encode())
                t.setattr(cid, msg.oid, "ver", str(version).encode())
                if autoclean:
                    self._txn_clear_clean(t, cid, msg.oid)
                self._log_txn(t, cid, pg, entry)
                t_c0 = trace_now()
                self.store.queue_transaction(t)
                self._op_stage("commit", t_c0, trace_now(),
                               version=version)
                a, deposed, _f = self._collect_subop_acks(tids)
                self._op_stage("subop", t_sub0, trace_now(), span=sub_span,
                               fanout=len(tids), acked=a)
                acked = 1 + a
                if deposed and acked < pool.min_size:
                    return MOSDOpReply(tid=msg.tid, retval=-116,
                                       epoch=self.my_epoch(),
                                       result={"deposed": True})
                if acked >= pool.min_size:
                    return MOSDOpReply(
                        tid=msg.tid, retval=0, epoch=self.my_epoch(),
                        result={"version": pg.version, "acked": acked},
                    )
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result={"applied": pg.version, "acked": acked,
                            "error": "below min_size commits"})
        if msg.op == "read":
            try:
                data = self.store.read(cid, msg.oid)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
            if msg.off or (msg.length or 0) > 0:
                off = msg.off or 0
                ln = msg.length if msg.length else len(data) - off
                data = data[off : off + ln]
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               data=pack_data(data), result={})
        if msg.op == "delete":
            with pg.lock:
                version = pg.version + 1
                entry = LogEntry(version, "delete", msg.oid,
                                 reqid=getattr(msg, "reqid", None))
                for osd in acting:
                    if osd == self.id or not self.osdmap.is_up(osd):
                        continue
                    tid = self._next_tid()
                    try:
                        self._conn_to_osd(osd).send_message(
                            MECSubOpWrite(
                                tid=tid, pgid=pg.pgid, oid=msg.oid, shard=0,
                                data=None, crc=None, version=version,
                                entry=entry.to_list(), epoch=self.my_epoch(),
                            )
                        )
                    except (OSError, ConnectionError):
                        pass
                t = Transaction()
                t.try_create_collection(cid)
                try:
                    self.store.stat(cid, msg.oid)
                    t.remove(cid, msg.oid)
                except (NotFound, KeyError):
                    pass
                self._log_txn(t, cid, pg, entry)
                self.store.queue_transaction(t)
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={})
        if msg.op == "stat":
            try:
                st = self.store.stat(cid, msg.oid)
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(), result=st)
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
        if msg.op == "list":
            oids = sorted(
                o for o in self.store.list_objects(cid)
                if not o.startswith("_") and CLONE_SEP not in o
            )
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"oids": oids})
        if msg.op in ("setxattr", "getxattrs"):
            return self._xattr_op(pg, acting, 0, msg)
        if msg.op.startswith("omap_"):
            return self._omap_op(pg, pool, acting, msg)
        if msg.op == "exec":
            return self._exec_op(pg, pool, acting, msg)
        if msg.op in ("watch", "unwatch", "notify"):
            return self._watch_op(pg, pool, msg)
        return MOSDOpReply(tid=msg.tid, retval=-22, epoch=self.my_epoch(),
                           result=f"bad op {msg.op}")

