"""Peering-lite, delta recovery, backfill, and stray-shard probing (reference: src/osd/PeeringState.cc + ECBackend recovery).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations


import time

import numpy as np

from ..common.crc32c import crc32c
from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.tracer import TRACER, TraceCtx, op_trace, set_op_trace, \
    trace_now
from ..store.object_store import NotFound
from .messages import (
    MECSubOpRead,
    MECSubOpWrite,
    MPGClean,
    MPGPull,
    MPGPullReply,
    MPGQuery,
    pack_data,
    unpack_data,
)
from ..osd.osdmap import PG_POOL_ERASURE
from ..osd.osdmap import OSDMap  # noqa: F401 (annotations)
from .pg import _current_generation, PGState


def prune_costly_helpers(avail: set[int], acting: list[int],
                         my_shard: int, peer_load: dict,
                         now: float, ttl: float,
                         max_qlen: int) -> set[int]:
    """Drop helper shards whose owner OSD measured EXPENSIVE in the
    freshest piggybacked sub-op telemetry (cephstorm; ROADMAP repair
    residual): a helper is dropped only when its `_peer_load` row is
    fresh (<= ttl old) AND reports a degraded backend sentinel or an
    mClock queue at/over `max_qlen`.  Shards without fresh telemetry
    are KEPT — with no telemetry at all the result equals `avail`, so
    the codec's default index-order plan is unchanged.  `my_shard` is
    never dropped (it anchors generation/size locally, costing no
    network read).  Pure: unit-testable without a daemon."""
    keep = set()
    for s in avail:
        if s == my_shard:
            keep.add(s)
            continue
        rec = peer_load.get(acting[s])
        if rec is None or now - rec[0] > ttl:
            keep.add(s)
            continue
        _ts, qlen, degraded = rec
        if degraded or qlen >= max_qlen:
            continue
        keep.add(s)
    return keep


class RecoveryMixin:
    # -- recovery (peering-lite, primary only) ----------------------------
    def _recover_all(self) -> None:
        m = self.osdmap
        if m is None:
            return
        # discover PGs I'm primary for (incl. ones with no local data yet)
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                try:
                    acting, primary = self._acting(pool_id, ps)
                except KeyError:
                    continue
                if primary != self.id or self.id not in acting:
                    continue
                pg = self._pg(pool_id, ps)
                # NO pg.lock here: _recover_pg's pull phase waits on the
                # donor's sub-writes, which our dispatch thread can only
                # apply after taking pg.lock — holding it across the pull
                # self-deadlocks.  _recover_pg locks its push phase.
                try:
                    self._recover_pg(pg, pool, acting)
                    with self._lock:
                        self._recovery_failures.pop(pg.pgid, None)
                except FailpointCrash:
                    # a simulated abort must propagate like a real one
                    # (the failpoint contract) — never count as a
                    # recoverable per-PG failure
                    raise
                except Exception as e:
                    # cephheal: a per-tick failure is a counted,
                    # traced, health-visible event — not a dout line
                    # that scrolls away (satellite: repeat-failing PGs
                    # surface in RECOVERY_STALLED via _mgr_report)
                    self.logger.inc("recovery_errors")
                    TRACER.tracepoint(
                        "recovery", "error", entity=self.whoami,
                        pgid=pg.pgid, error=repr(e))
                    with self._lock:
                        ent = self._recovery_failures.setdefault(
                            pg.pgid, [0, ""])
                        ent[0] += 1
                        ent[1] = repr(e)
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} recover {pg.pgid}: {e!r}",
                    )

    def _rebuild_intervals_from_maps(self, pg: PGState, start: int,
                                     until: int | None = None) -> None:
        """Reconstruct interval history from the mon's stored maps
        (reference: PastIntervals::check_new_interval walked over past
        OSDMaps via OSDService::get_map).  A revived OSD's in-memory
        tracking saw nothing while it was down, and a freshly-assigned
        primary only started recording at its own PG creation; the maps
        saw everything.  Rebuilds the closures over [start, until) and
        PREPENDS them to whatever in-memory history already exists."""
        from .past_intervals import PastIntervals

        cur = self.my_epoch()
        until = cur if until is None else min(until, cur)
        start = max(1, start)
        if until - start > 512:
            start = until - 512  # bound mon fetches on huge gaps
        # batched fetch: ~8 round trips for the full 512-epoch bound
        # instead of one command per epoch (review r4)
        fetched: dict[int, dict] = {}
        e = start
        while e <= until:
            if self.osdmap is not None and e == self.osdmap.epoch:
                e += 1
                continue
            try:
                rv, res = self.mc.command(
                    {"prefix": "osd getmaps", "first": e, "last": until},
                    timeout=10.0,
                )
            except (OSError, ConnectionError):
                return  # mon unreachable: retry next pass
            if rv != 0:
                return
            fetched.update(
                {int(k): v for k, v in res.get("maps", {}).items()}
            )
            e = int(res.get("last", e)) + 1
        rebuilt = PastIntervals()
        prev = None
        prev_ua = None
        first = start
        for e in range(start, until + 1):
            if self.osdmap is not None and e == self.osdmap.epoch:
                m = self.osdmap
            else:
                j = fetched.get(e)
                if j is None:
                    continue  # epoch gap (paxos-trimmed): skip
                m = OSDMap.from_json(j)
            try:
                ua = m.pg_to_up_acting_osds(pg.pool_id, pg.ps)
            except Exception:
                prev, prev_ua = m, None
                continue
            if prev_ua is not None and (prev_ua[2], prev_ua[3]) != \
                    (ua[2], ua[3]):
                pool = prev.pools.get(pg.pool_id)
                went_rw = (
                    prev_ua[3] >= 0
                    and pool is not None
                    and sum(1 for a in prev_ua[2] if a >= 0) >= pool.min_size
                )
                rebuilt.add(
                    first=first, last=m.epoch - 1,
                    up=prev_ua[0], acting=prev_ua[2], primary=prev_ua[3],
                    maybe_went_rw=went_rw,
                )
                first = m.epoch
            prev, prev_ua = m, ua
        pg.intervals_rebuilt = True
        if rebuilt:
            from .past_intervals import MAX_INTERVALS

            # keep the NEWEST MAX_INTERVALS — direct assignment must not
            # bypass add()'s growth cap (review r4)
            pg.past_intervals.intervals = (
                rebuilt.intervals + pg.past_intervals.intervals
            )[-MAX_INTERVALS:]
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} {pg.pgid} rebuilt "
                f"{len(rebuilt.intervals)} past interval(s) from maps "
                f"[{start},{until}]",
            )
            self._save_intervals(pg)

    def _recover_pg(self, pg: PGState, pool, acting: list[int]) -> None:
        """cephheal wrapper: one recovery pass = one traceable,
        TrackedOp-registered background op.  The ctx is born HERE (the
        recovery analog of op_submit) with the same head-coin-flip +
        tail-provisional contract, so a slow recovery keeps its
        connected tree at trace_sampling_rate=0; the TrackedOp
        (src="recovery") puts multi-second pulls into
        dump_historic_slow_ops.  The body is _recover_pg_inner —
        exceptions propagate to _recover_all's error accounting."""
        # "osd.recovery.tick": an error action fails this PG's whole
        # pass at the top of every tick — the deterministic driver for
        # the repeat-failing-PG health surface (docs/fault_injection.md)
        failpoint("osd.recovery.tick", cct=self.cct, entity=self.whoami,
                  pgid=pg.pgid)
        ctx = self._bg_trace_ctx()
        root = None
        if ctx is not None:
            root = TRACER.begin(ctx, "recovery", entity=self.whoami,
                                pgid=pg.pgid)
        tracked = self.op_tracker.create(
            f"recovery({pg.pgid})", src="recovery")
        tracked.trace_id = ctx.trace_id if ctx is not None else None
        prev = op_trace()
        set_op_trace({
            "ctx": root.ctx() if root is not None else ctx,
            "tracked": tracked,
        })
        try:
            self._recover_pg_inner(pg, pool, acting)
        finally:
            set_op_trace(prev)
            TRACER.end(root)
            tracked.finish()
            if TRACER.enabled and tracked.trace_id is not None:
                self._bg_tail_verdict(tracked)

    def _recover_pg_inner(self, pg: PGState, pool,
                          acting: list[int]) -> None:
        is_ec = pool.type == PG_POOL_ERASURE
        codec = self._codec_for_pool(pool) if is_ec else None
        # one query round: peer versions + object lists drive the
        # authoritative-log pull, the per-peer classification, and
        # delete propagation
        peers: dict[tuple[int, int], tuple[int, list]] = {}
        peer_epochs: list[int] = []
        t_peer0 = trace_now()
        queried = 0
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            # replicated replicas all store in the s0 collection; only EC
            # shards have per-shard collections
            store_shard = shard if is_ec else 0
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid, shard=store_shard,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            queried += 1
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.version is None:
                continue
            peers[(shard, osd)] = (rep.version, rep.oids or [])
            e = getattr(rep, "last_epoch", None)
            if e:
                peer_epochs.append(int(e))
        if queried:
            # sampled only when a query actually went out — the
            # every-tick idle pass must not drown the histogram
            self._bg_stage("recovery_peer", t_peer0, trace_now(),
                           peers=len(peers), queried=queried)
        interval_at_entry = pg.interval_start
        # history rebuild (reference: pg_history_t carried in notifies +
        # PastIntervals built over past OSDMaps): when this primary has
        # no interval history but the PG demonstrably has a past — its
        # own or any peer's last-write epoch predates the current
        # interval — fetch the intervening maps from the mon and
        # reconstruct the closed intervals before judging anything.
        # Covers both the revived stale OSD (its own epoch is old) and
        # the freshly-assigned empty primary (a peer's epoch is old) —
        # even one that already recorded SOME closures of its own: the
        # rebuild fills the prefix its in-memory tracking predates.
        known = [e for e in ([pg.last_map_epoch] + peer_epochs) if e]
        hist_floor = (
            pg.past_intervals.intervals[0]["first"]
            if pg.past_intervals else pg.interval_start
        )
        if (
            not pg.intervals_rebuilt
            and known
            and min(known) < hist_floor
        ):
            self._rebuild_intervals_from_maps(
                pg, start=min(known), until=hist_floor
            )
        # choose_acting beyond the acting set (reference: build_prior +
        # choose_acting over PastIntervals): members of past rw
        # intervals may hold a log NEWER than anything the current
        # acting set has — query them too, bounded by the history
        strays: dict[tuple[int, int], int] = {}
        queried = {self.id} | {osd for (_s, osd) in peers}
        prior = pg.past_intervals.query_candidates(
            exclude={-1, self.id} | {o for o in acting if o >= 0},
            is_up=self.osdmap.is_up,
        )
        for osd, p_shard in prior.items():
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MPGQuery(tid=tid, pgid=pg.pgid,
                             shard=p_shard if is_ec else 0,
                             epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid, timeout=5.0)
            if rep is None or rep.version is None:
                continue
            queried.add(osd)
            strays[(p_shard, osd)] = rep.version
        # build_prior activation block: a past rw interval NONE of whose
        # members answered may hold the authoritative log — activating
        # anyway could serve a stale/forked history (the exact failure
        # generation floors cannot see).  Stay inactive and retry.
        blocked = pg.past_intervals.blocked_by(queried)
        if blocked:
            iv = blocked[0]
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} {pg.pgid} peering blocked: interval "
                f"[{iv['first']},{iv['last']}] acting {iv['acting']} "
                f"went rw and no member is reachable",
            )
            return
        # phase 0 — adopt the authoritative log (reference: peering's
        # choose_acting/authoritative-log step): a primary revived after
        # missing writes must catch ITSELF up first, else it would mint
        # duplicate versions on the next write and wrongly judge
        # ahead-peers clean (wait_clean compares against the primary).
        # Runs WITHOUT pg.lock: the donor's catch-up arrives as
        # MECSubOpWrites our dispatch thread applies under that lock.
        ahead = {k: v for k, (v, _o) in peers.items() if v > pg.version}
        stray_newest = max(strays.values(), default=0)
        if stray_newest > max([pg.version, *ahead.values()]):
            if is_ec:
                # an EC stray proves newer writes exist, but a non-acting
                # donor cannot push shard-correct chunks (the donor path
                # reads by its acting index) — stay INACTIVE rather than
                # activate on a log we know is stale; the PG heals when
                # the stray rejoins acting or an acting member catches up
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} {pg.pgid} stale vs stray holders "
                    f"(v{stray_newest} > v{pg.version}); deferring "
                    f"activation",
                )
                return
            # replicated: the past-interval holder IS the authoritative
            # log donor even though it is not acting (choose_acting
            # electing a stray; every replica is shard 0, so the pull
            # path needs no shard translation)
            ahead = {
                k: v for k, v in strays.items() if v == stray_newest
            }
        if ahead:
            (_b_shard, b_osd), _bv = max(ahead.items(), key=lambda kv: kv[1])
            my_shard = acting.index(self.id) if is_ec else 0
            try:
                my_oids = [
                    o for o in self.store.list_objects(
                        self._cid(pg.pgid, my_shard))
                    if not o.startswith("_")
                ]
            except (NotFound, KeyError):
                my_oids = []
            tid = self._next_tid()
            # span opened BEFORE the send so the MPGPull carries its id
            # as parent — the donor's rebuild/push spans join THIS node
            # (the subop fan-out pattern from PR 9)
            pull_span = TRACER.begin(
                self._op_trace_ctx(), "recovery_pull",
                entity=self.whoami, donor=f"osd.{b_osd}",
            ) if TRACER.enabled else None
            t_pull0 = pull_span.t0 if pull_span is not None else trace_now()
            try:
                self._conn_to_osd(b_osd).send_message(MPGPull(
                    tid=tid, pgid=pg.pgid, shard=my_shard,
                    from_version=pg.version, epoch=self.my_epoch(),
                    have_oids=my_oids,
                    trace_id=(pull_span.trace_id
                              if pull_span is not None else None),
                    parent_span=(pull_span.span_id
                                 if pull_span is not None else None),
                ))
                rep = self._wait_reply(tid, timeout=30.0)
            except (OSError, ConnectionError):
                rep = None
            self._bg_stage(
                "recovery_pull", t_pull0, trace_now(), span=pull_span,
                donor=f"osd.{b_osd}",
                retval=rep.retval if rep is not None else None)
            if rep is not None and rep.retval == 0:
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} pulled {pg.pgid} forward to "
                    f"v{pg.version} from osd.{b_osd}",
                )
            else:
                return  # retry next tick; judging peers now would be wrong
        # peered: no peer is ahead (or we just adopted the ahead log) —
        # this primary may now serve ops for the current interval
        pg.activated_interval = interval_at_entry
        if pg.version == 0:
            return  # nothing written yet
        my_shard = acting.index(self.id) if is_ec else 0
        my_cid = self._cid(pg.pgid, my_shard)

        def _my_oids() -> set:
            try:
                return {
                    o for o in self.store.list_objects(my_cid)
                    if not o.startswith("_")
                }
            except (NotFound, KeyError):
                return set()

        my_oids = _my_oids()
        # phase 0.5 — SELF role-heal: an acting permutation can hand this
        # primary a shard role it never held; every peer below is judged
        # against MY collection, so an empty one would read as
        # everything-clean while the primary serves nothing.  Pull full
        # content from an up-to-date peer — the donor's backfill push
        # carries data + xattrs + omap and deletes my stale extras
        # (reference: the primary recovers itself first in
        # PeeringState::activate / recovery_state).
        peer_union: set = set()
        for (_v, oids) in peers.values():
            peer_union.update(oids)
        if peer_union - my_oids:
            donor = next(
                (osd for (shard, osd), (v, _o) in peers.items()
                 if v >= pg.version),
                None,
            )
            if donor is not None:
                self.cct.dout(
                    "osd", 1,
                    f"{self.whoami} self role-heal {pg.pgid} shard "
                    f"{my_shard}: {len(peer_union - my_oids)} objects "
                    f"from osd.{donor}",
                )
                tid = self._next_tid()
                heal_span = TRACER.begin(
                    self._op_trace_ctx(), "recovery_pull",
                    entity=self.whoami, donor=f"osd.{donor}",
                    role_heal=True,
                ) if TRACER.enabled else None
                t_heal0 = (heal_span.t0 if heal_span is not None
                           else trace_now())
                try:
                    self._conn_to_osd(donor).send_message(MPGPull(
                        tid=tid, pgid=pg.pgid, shard=my_shard,
                        from_version=0, epoch=self.my_epoch(),
                        have_oids=sorted(my_oids),
                        trace_id=(heal_span.trace_id
                                  if heal_span is not None else None),
                        parent_span=(heal_span.span_id
                                     if heal_span is not None else None),
                    ))
                    self._wait_reply(tid, timeout=30.0)
                except (OSError, ConnectionError):
                    pass
                self._bg_stage("recovery_pull", t_heal0, trace_now(),
                               span=heal_span, donor=f"osd.{donor}",
                               role_heal=True)
                my_oids = _my_oids()
        # cephheal pg_stats: object-copies this PG's LIVE peers are
        # missing (down/absent shards are counted live by _mgr_report
        # from its store walk — this is the recoverable-by-push half
        # the report cannot see).  Per-pass granularity; the push
        # helpers decrement as objects land so a long backfill drains
        # visibly between passes.
        degraded = 0
        for (shard, osd), (peer_ver, peer_oids) in peers.items():
            role_missing_n = len(my_oids - set(peer_oids))
            if peer_ver >= pg.version:
                degraded += role_missing_n
            elif pg.log.covers(peer_ver):
                newest, _d = pg.log.missing_since(peer_ver)
                degraded += max(len(newest), role_missing_n)
            else:
                degraded += max(len(my_oids), role_missing_n)
        pg.stat_degraded_peers = degraded
        # push phase: serialize vs concurrent client writes on this PG
        all_clean = True
        with pg.lock:
            for (shard, osd), (peer_ver, peer_oids) in peers.items():
                role_missing = my_oids - set(peer_oids)
                if peer_ver >= pg.version and not role_missing:
                    continue  # clean
                all_clean = False
                if peer_ver >= pg.version:
                    # version-current but the SHARD ROLE's objects are
                    # absent: an acting-set permutation (OSD out -> CRUSH
                    # reshuffle) handed this OSD a shard it never held —
                    # the per-PG version cannot see that, only the
                    # contents comparison can.  Rebuild its new role's
                    # chunks (and retire any stale leftovers in that
                    # collection from an older interval).
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} role-backfill {pg.pgid} shard "
                        f"{shard} osd.{osd}: {len(role_missing)} objects",
                    )
                    t_rb0 = trace_now()
                    self._push_objects(
                        pg, codec, acting, shard if is_ec else 0, osd,
                        {o: None for o in sorted(role_missing)},
                        set(peer_oids) - my_oids, is_ec,
                    )
                    self._bg_stage("recovery_push", t_rb0, trace_now(),
                                   peer=f"osd.{osd}", shard=shard,
                                   mode="role_backfill",
                                   objects=len(role_missing))
                else:
                    self._push_missing(
                        pg, codec, acting, shard if is_ec else 0, osd,
                        peer_ver, is_ec, peer_oids,
                    )
        if all_clean:
            pg.stat_degraded_peers = 0
        # prune the interval history once the PG is CLEAN in the current
        # interval (reference: last_epoch_clean).  "Clean" demands a
        # FULL acting set in which every member answered and needed no
        # push — a degraded PG keeps its history: those unheard members
        # are exactly what the history exists to track (review r4).
        # The clean point is BROADCAST to the acting replicas (MPGClean)
        # so their persisted rebuild floors advance too — otherwise a
        # later primary rebuilding from a replica's stale last-write
        # epoch would resurrect already-settled intervals whose members
        # are long gone and block activation forever (review r4).
        acting_members = {o for o in acting if o >= 0 and o != self.id}
        if (
            all_clean
            and all(o >= 0 for o in acting)
            and acting_members <= {osd for (_s, osd) in peers}
            and (pg.past_intervals
                 or pg.clean_broadcast_interval != interval_at_entry)
        ):
            epoch = self.my_epoch()
            # under the pg lock: _log_txn (op worker, holding pg.lock)
            # writes last_map_epoch concurrently, and this max() is a
            # read-modify-write (cephrace CR1 write-write).  The store
            # txn below stays OUTSIDE the lock (blocking under a lock is
            # CL1's business)
            with pg.lock:
                pg.past_intervals.clear()
                pg.last_map_epoch = max(pg.last_map_epoch, epoch)
                pg.intervals_rebuilt = False
                pg.clean_broadcast_interval = interval_at_entry
            self._save_intervals(pg)
            for shard, osd in enumerate(acting):
                if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                    continue
                try:
                    self._conn_to_osd(osd).send_message(MPGClean(
                        pgid=pg.pgid, shard=shard if is_ec else 0,
                        epoch=epoch,
                    ))
                except (OSError, ConnectionError):
                    pass  # replica re-learns at its next clean pass

    def _push_missing(self, pg, codec, acting, dest_shard, dest_osd,
                      from_version, is_ec, dest_oids) -> bool:
        """Classify delta vs backfill, push, seal — shared by the primary
        push loop and the pull donor; one `recovery_push` stage sample /
        span per round, whichever side runs it (cephheal)."""
        t0 = trace_now()
        ok = self._push_missing_inner(
            pg, codec, acting, dest_shard, dest_osd, from_version,
            is_ec, dest_oids,
        )
        self._bg_stage(
            "recovery_push", t0, trace_now(), peer=f"osd.{dest_osd}",
            shard=dest_shard, ok=ok,
            mode="delta" if pg.log.covers(from_version) else "backfill")
        return ok

    def _push_missing_inner(self, pg, codec, acting, dest_shard, dest_osd,
                            from_version, is_ec, dest_oids) -> bool:
        """Counters are started/completed
        pairs: stat_delta_recoveries / stat_backfills count rounds
        STARTED (race-free for observers — an ack lost after the peer
        applied would leave a completed-only counter at zero), the
        *_completed twins count fully acked rounds."""
        my_shard = acting.index(self.id) if is_ec else 0
        if pg.log.covers(from_version):
            self.cct.dout(
                "osd", 1,
                f"{self.whoami} delta-recovery {pg.pgid} "
                f"shard {dest_shard} osd.{dest_osd} from v{from_version}",
            )
            pg.stat_delta_recoveries = getattr(
                pg, "stat_delta_recoveries", 0) + 1
            ok = self._push_log_delta(
                pg, codec, acting, dest_shard, dest_osd, from_version, is_ec
            )
            if ok:
                self._bump_peer_version(pg, dest_shard, dest_osd, pg.version)
                pg.stat_delta_completed = getattr(
                    pg, "stat_delta_completed", 0) + 1
            return ok
        # log too old: full backfill of this shard.  Versions are
        # unknowable per object (trimmed), so chunks are pushed
        # unversioned and the final sync entry seals the version.  The
        # target's extra objects (deleted here after its log horizon)
        # get data-less deletes — a survivors-only push would resurrect
        # deletions when the target is later trusted.
        try:
            oids = [
                o for o in self.store.list_objects(
                    self._cid(pg.pgid, my_shard))
                if not o.startswith("_")
            ]
        except (NotFound, KeyError):
            oids = []
        deleted = set(dest_oids or []) - set(oids)
        self.cct.dout(
            "osd", 1,
            f"{self.whoami} backfill {pg.pgid} shard {dest_shard} "
            f"osd.{dest_osd}: {len(oids)} objects, "
            f"{len(deleted)} deletions",
        )
        pg.stat_backfills = getattr(pg, "stat_backfills", 0) + 1
        ok = self._push_objects(
            pg, codec, acting, dest_shard, dest_osd,
            {o: None for o in oids}, deleted, is_ec,
        )
        if ok:
            self._bump_peer_version(pg, dest_shard, dest_osd, pg.version)
            pg.stat_backfill_completed = getattr(
                pg, "stat_backfill_completed", 0) + 1
        return ok

    def _handle_pg_pull(self, conn, msg: MPGPull) -> None:
        """An ahead peer serving a stale primary's catch-up request: push
        my log delta (or full objects + deletions when my log was
        trimmed) to the requester, then seal its version (the
        authoritative-log donor role in peering).  Runs under MY pg.lock
        so a concurrent write cannot advance the version mid-push and
        let the seal vouch for entries never sent; the requester holds
        no lock while waiting, so there is no cross-OSD lock cycle."""
        retval = -5
        # cephheal: the donor's half of the recovery tree — its rebuild
        # and push spans parent to the requester's recovery_pull span
        # carried on the wire, and the work rides a src="recovery"
        # TrackedOp so a multi-second donor push is slow-op-visible
        donor_span = None
        if TRACER.enabled and getattr(msg, "trace_id", None) is not None:
            donor_span = TRACER.begin(
                TraceCtx(msg.trace_id, msg.parent_span), "recovery_donor",
                entity=self.whoami, pgid=msg.pgid, requester=msg.src,
            )
        tracked = self.op_tracker.create(
            f"recovery_donor({msg.pgid} -> {msg.src})", src="recovery")
        tracked.trace_id = getattr(msg, "trace_id", None)
        prev = op_trace()
        set_op_trace({
            "ctx": donor_span.ctx() if donor_span is not None else None,
            "tracked": tracked,
        })
        try:
            # "osd.recovery.pull": an error action makes this donor fail
            # the catch-up request (the requester retries next pass,
            # possibly from another peer)
            failpoint("osd.recovery.pull", cct=self.cct,
                      entity=self.whoami, pgid=msg.pgid)
            pool_id, ps = msg.pgid.split(".")
            pg = self._pg(int(pool_id), int(ps))
            pool = self.osdmap.pools.get(int(pool_id))
            requester = (
                int(msg.src.split(".", 1)[1])
                if msg.src.startswith("osd.") else None
            )
            if pool is None or requester is None:
                raise ValueError(f"bad pull {msg.src} {msg.pgid}")
            acting, _p = self._acting(int(pool_id), int(ps))
            is_ec = pool.type == PG_POOL_ERASURE
            codec = self._codec_for_pool(pool) if is_ec else None
            from_v = int(msg.from_version or 0)
            with pg.lock:
                if pg.version <= from_v:
                    retval = 0  # nothing newer here
                else:
                    ok = self._push_missing(
                        pg, codec, acting, msg.shard, requester, from_v,
                        is_ec, msg.have_oids,
                    )
                    retval = 0 if ok else -5
        except FailpointCrash:
            raise
        except Exception as e:
            self.cct.dout(
                "osd", 0, f"{self.whoami} pg pull failed: {e!r}"
            )
        finally:
            set_op_trace(prev)
            TRACER.end(donor_span, retval=retval)
            tracked.finish()
            if TRACER.enabled and tracked.trace_id is not None \
                    and self.op_tracker.complaint_time > 0 \
                    and tracked.duration() > self.op_tracker.complaint_time:
                # promote only — the requester's verdict owns the
                # discard (promote wins over discard, PR-11 rule)
                TRACER.promote(tracked.trace_id, reason="recovery_donor")
        try:
            conn.send_message(MPGPullReply(
                tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
                retval=retval,
                trace_id=getattr(msg, "trace_id", None),
                parent_span=getattr(msg, "parent_span", None),
            ))
        except (OSError, ConnectionError):
            pass

    def _push_sub_write(self, pg, osd, shard, oid, data, version, entry,
                        src_cid: str | None = None,
                        osize: int | None = None) -> bool:
        """One recovery push; True iff the peer acked it (retval 0).
        Data pushes copy the object's user xattrs from `src_cid` (the
        primary's own shard collection) so a recovered shard can answer
        getxattrs after a primary move.  They also carry the primary's
        stored chunk-generation stamp (`over`): the pushed bytes are
        rebuilt-CURRENT, and stamping the log-entry version instead
        would diverge from undisturbed shards whenever the log advanced
        through xattr-only modifies (which don't change stripe bytes)."""
        xattrs = None
        gen = None
        omap = None
        if data is not None and src_cid is not None:
            gen = self._stored_ver(src_cid, oid)
            try:
                mine = self.store.getattrs(src_cid, oid)
            except (NotFound, KeyError):
                mine = {}
            # always a dict (may be empty): the receiver treats it as the
            # FULL snapshot, clearing stale attrs a removal left behind
            xattrs = {
                n[2:]: pack_data(v)
                for n, v in mine.items() if n.startswith("u_")
            }
            try:
                kv = self.store.omap_get(src_cid, oid)
            except (NotFound, KeyError):
                kv = {}
            # omap recovered as a full snapshot, like the xattrs — sent
            # even when empty so a replica's stale keys are cleared
            omap = {"snapshot": {k: pack_data(v) for k, v in kv.items()}}
        tid = self._next_tid()
        # cephheal: recovery pushes carry the background trace context
        # (MECSubOpWrite learned the fields in PR 9), so the receiving
        # shard's replica_commit span joins the recovery tree
        ctx = self._op_trace_ctx()
        try:
            # "osd.recovery.push": an error action drops this push on the
            # floor — the object stays missing until a later pass
            failpoint("osd.recovery.push", cct=self.cct,
                      entity=self.whoami, pgid=pg.pgid, oid=oid, to=osd)
            self._conn_to_osd(osd).send_message(
                MECSubOpWrite(
                    tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                    data=pack_data(data) if data is not None else None,
                    crc=crc32c(data) if data is not None else None,
                    version=version, entry=entry, epoch=self.my_epoch(),
                    xattrs=xattrs, over=gen, osize=osize, omap=omap,
                    trace_id=ctx.trace_id if ctx is not None else None,
                    parent_span=ctx.span_id if ctx is not None else None,
                )
            )
        except FailpointCrash:
            raise
        except (FailpointError, OSError, ConnectionError):
            return False
        rep = self._wait_reply(tid, timeout=5.0)
        return rep is not None and rep.retval == 0

    def _push_log_delta(self, pg, codec, acting, shard, osd,
                        peer_version: int, is_ec: bool) -> bool:
        """Delta recovery: replay the FULL entry stream since the peer's
        version, in order, so the peer's pg_log stays contiguous and its
        covers() answer stays honest if it later becomes primary
        (reference: PGLog merge + pg_missing_t-driven recover_object).

        Data rides only the newest modify of each object; earlier modifies
        and deletes replay as log-only / delete pushes.  Returns True only
        if every push acked, so the caller never marks the peer clean past
        data it does not hold."""
        newest, _deleted = pg.log.missing_since(peer_version)
        my_cid = self._cid(
            pg.pgid, acting.index(self.id) if is_ec else 0
        )
        for e in pg.log.entries_since(peer_version):
            if e.op == "delete":
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, None, e.version, e.to_list()
                )
            elif e.op in ("modify", "attr") and newest.get(e.oid) == e.version:
                chunk, size = self._rebuild_shard_chunk(
                    pg, codec, acting, e.oid, shard, is_ec
                )
                if chunk is None:
                    # UNFOUND right now (reference: missing_loc unfound
                    # set): park THIS object but keep recovering the
                    # rest — one unrecoverable object must not wedge
                    # the whole peer's recovery.  The entry still
                    # replays (log stays contiguous); the object stays
                    # missing on the peer exactly as it is everywhere
                    # else, and a later tick retries when a source
                    # resurfaces.
                    self.cct.dout(
                        "osd", 1,
                        f"{self.whoami} recovery: {pg.pgid}/{e.oid} "
                        f"unfound, parking",
                    )
                    ok = self._push_sub_write(
                        pg, osd, shard, e.oid, None, e.version,
                        e.to_list(),
                    )
                    if not ok:
                        return False
                    continue
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, chunk, e.version,
                    e.to_list(), src_cid=my_cid, osize=size,
                )
                self.logger.inc("recovery_ops")
                if ok:
                    # live drain for the progress plane: one recovered
                    # object-copy off the degraded count
                    pg.stat_degraded_peers = max(
                        0, pg.stat_degraded_peers - 1)
            else:
                # superseded modify / clean marker: log-entry-only replay
                ok = self._push_sub_write(
                    pg, osd, shard, e.oid, None, e.version, e.to_list()
                )
            if not ok:
                return False
        return True

    def _push_objects(self, pg, codec, acting, shard, osd,
                      newest: dict[str, int | None], deleted: set[str],
                      is_ec: bool) -> bool:
        """Backfill push: chunk data for every object, unversioned (the
        trimmed log cannot vouch for per-object versions); the final
        "clean" seal establishes the peer's version and empty log window.
        The push still carries the object size (osize) so the peer can
        answer stat/padding-strip."""
        for oid in sorted(deleted):
            if not self._push_sub_write(pg, osd, shard, oid, None, None, None):
                return False
        my_cid = self._cid(
            pg.pgid, acting.index(self.id) if is_ec else 0
        )
        all_ok = True
        for oid in sorted(newest, key=lambda o: (newest[o] or 0, o)):
            chunk, size = self._rebuild_shard_chunk(
                pg, codec, acting, oid, shard, is_ec
            )
            if chunk is None:
                # unfound: park this object, recover the rest (see
                # _push_log_delta); all_ok=False keeps the peer unsealed
                # so later ticks retry
                all_ok = False
                continue
            version = newest[oid]
            entry = [version or 0, "modify", oid]
            if self._push_sub_write(
                pg, osd, shard, oid, chunk, version, entry, src_cid=my_cid,
                osize=size,
            ):
                # live drain for the progress plane (see _push_log_delta)
                pg.stat_degraded_peers = max(
                    0, pg.stat_degraded_peers - 1)
            else:
                all_ok = False
        return all_ok

    def _bump_peer_version(self, pg, shard, osd, version: int) -> None:
        """Final version/log sync after successful pushes: a data-less
        "clean" entry (ignored by missing_since) seals the peer at the
        primary's version."""
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpWrite(
                    tid=tid, pgid=pg.pgid, oid="", shard=shard,
                    data=None, crc=None, version=version,
                    entry=[version, "clean", ""],
                    epoch=self.my_epoch(),
                )
            )
            self._wait_reply(tid, timeout=5.0)
        except (OSError, ConnectionError):
            pass

    def _rebuild_shard_chunk(
        self, pg, codec, acting, oid: str, shard: int, is_ec: bool,
        exclude: set[int] | None = None,
    ) -> tuple[bytes | None, int]:
        """Recompute shard `shard`'s bytes for oid (reference:
        ECBackend::recover_object — read k chunks, re-encode).  `exclude`
        names additional shards whose data must not feed the rebuild
        (scrub-flagged rot).

        cephheal: the rebuild first follows the codec's
        minimum_to_decode plan (_plan_repair_read) — k full helper
        chunks for an MDS code, d helpers x sub-chunk ranges for CLAY —
        and only falls back to the historical gather-everything path
        when the plan cannot be satisfied (stale generations, silent
        helpers, self-heal).  Every completed rebuild lands one
        repair-bandwidth accounting record (helper reads, bytes read,
        bytes repaired) keyed by (pool, codec), and one
        `recovery_rebuild` stage sample/span."""
        t_rb0 = trace_now()
        pool = self.osdmap.pools.get(pg.pool_id) if self.osdmap else None
        clabel = self._codec_label(pool)
        my_shard = acting.index(self.id)
        if not is_ec:
            try:
                data = self.store.read(self._cid(pg.pgid, 0), oid)
            except (NotFound, KeyError):
                return None, 0
            self.recovery_acct.record_repair(
                pg.pool_id, clabel, 1, len(data), len(data))
            self._bg_stage("recovery_rebuild", t_rb0, trace_now(),
                           oid=oid, shard=shard)
            return data, len(data)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        floor = pg.log.obj_newest.get(oid)
        planned = self._plan_repair_read(pg, codec, acting, oid, shard,
                                         exclude, floor)
        if planned is not None:
            chunk, size, reads, nbytes = planned
            self.recovery_acct.record_repair(
                pg.pool_id, clabel, reads, nbytes, len(chunk))
            self._bg_stage("recovery_rebuild", t_rb0, trace_now(),
                           oid=oid, shard=shard, planned=True,
                           helper_reads=reads)
            return chunk, size
        # include the DEST shard in the gather: the receiver lacks its
        # chunk, but the exact chunk may survive as a stray on a previous
        # holder (acting permutations) — using it directly also rescues
        # objects written degraded at exactly min_size, where fewer than
        # k OTHER chunks exist and decode alone could never recover
        want = set(range(n)) - (exclude or set())
        sizes: dict[int, int] = {}
        vers: dict[int, int | None] = {}
        got = self._gather_chunks(pg, codec, acting, oid, want, sizes=sizes,
                                  vers=vers, stray=True, floor=floor)
        read_bytes = sum(len(b) for b in got.values())
        n_reads = len(got)
        # never rebuild from a MIX of stripe generations, nor from one
        # the log proves is below the newest write
        got = _current_generation(got, vers, floor)
        if shard in got:
            try:
                size = int(self.store.getattr(
                    self._cid(pg.pgid, acting.index(self.id)), oid, "size"))
            except (NotFound, KeyError, ValueError):
                size = sizes.get(shard, next(iter(sizes.values()), 0))
            self.recovery_acct.record_repair(
                pg.pool_id, clabel, n_reads, read_bytes,
                len(got[shard]), full_gather=True)
            self._bg_stage("recovery_rebuild", t_rb0, trace_now(),
                           oid=oid, shard=shard, stray_rescue=True)
            return bytes(got[shard]), size
        if len(got) < k:
            return None, 0
        try:
            size = int(self.store.getattr(
                self._cid(pg.pgid, my_shard), oid, "size"))
        except (NotFound, KeyError, ValueError):
            # our own xattr is gone (we may be the shard being repaired):
            # any healthy peer's size xattr is authoritative
            size = next(iter(sizes.values()), 0)
        chunks = {s: np.frombuffer(b, np.uint8) for s, b in got.items()}
        dec = codec.decode(
            {shard}, chunks, len(next(iter(chunks.values())))
        )
        out = np.asarray(dec[shard], np.uint8).tobytes()
        self.recovery_acct.record_repair(
            pg.pool_id, clabel, n_reads, read_bytes, len(out),
            full_gather=True)
        self._bg_stage("recovery_rebuild", t_rb0, trace_now(),
                       oid=oid, shard=shard)
        return out, size

    def _plan_repair_read(
        self, pg, codec, acting, oid: str, lost: int,
        exclude: set[int] | None, floor: int | None,
    ) -> tuple[bytes, int, int, int] | None:
        """Bandwidth-minimal rebuild of one lost EC shard following the
        codec's minimum_to_decode plan (reference: ECBackend asks the
        codec which chunks — and for CLAY which SUB-chunk ranges — a
        repair must read, instead of fetching every survivor).

        Returns (chunk_bytes, object_size, helper_reads, bytes_read) on
        success, or None to fall back to the broad-gather path.  The
        fast path bails on ANY surprise — a silent helper, a
        generation mismatch against this primary's chunk or the log
        floor, a sub-chunk geometry it cannot verify — because the
        fallback path owns stray hunting and mixed-generation
        arbitration; this path only claims the healthy common case,
        which is where the bandwidth goes (arXiv:1412.3022)."""
        my_shard = acting.index(self.id)
        if lost == my_shard:
            return None  # self-heal: no local generation/size anchor
        my_cid = self._cid(pg.pgid, my_shard)
        try:
            failpoint("osd.ec.shard_read", cct=self.cct,
                      entity=self.whoami, pgid=pg.pgid, shard=my_shard,
                      oid=oid)
            mine = bytes(self.store.read(my_cid, oid))
        except FailpointCrash:
            raise
        except (FailpointError, NotFound, KeyError):
            return None
        try:
            stored = int(self.store.getattr(my_cid, oid, "hinfo"))
        except (NotFound, KeyError, ValueError):
            stored = None
        if not mine or (stored is not None and crc32c(mine) != stored):
            return None
        my_ver = self._stored_ver(my_cid, oid)
        target = floor
        if my_ver is not None:
            if floor is not None and my_ver != floor:
                return None  # our own chunk is off-generation
            target = my_ver
        try:
            size = int(self.store.getattr(my_cid, oid, "size"))
        except (NotFound, KeyError, ValueError):
            return None
        avail = {
            s for s, o in enumerate(acting)
            if o >= 0 and s != lost and self.osdmap.is_up(o)
        } - (exclude or set())
        if my_shard not in avail:
            return None
        plan = None
        if bool(self.cct.conf.get("osd_repair_cost_aware")):
            # cost-aware helper choice (cephstorm; ROADMAP repair
            # residual): plan against the CHEAP subset first — helpers
            # whose piggybacked telemetry shows a deep mClock queue or
            # a degraded sentinel are pruned.  A codec that cannot plan
            # from the cheap subset (too few survivors) falls through
            # to the full availability set, so correctness never hinges
            # on telemetry.
            with self._lock:
                peer_load = dict(self._peer_load)
            cheap = prune_costly_helpers(
                avail, acting, my_shard, peer_load, time.monotonic(),
                float(self.cct.conf.get("osd_repair_telemetry_ttl")),
                int(self.cct.conf.get("osd_repair_helper_max_qlen")))
            if cheap != avail:
                try:
                    plan = codec.minimum_to_decode({lost}, cheap)
                except Exception:
                    plan = None
        if plan is None or lost in plan:
            try:
                plan = codec.minimum_to_decode({lost}, avail)
            except Exception:
                return None
        if lost in plan:
            return None  # plan wants the lost chunk itself: nonsense here
        helpers = sorted(plan)
        full_plan = all(
            len(r) == 1 and tuple(r[0]) == (0, -1)
            for r in plan.values()
        )
        if full_plan:
            return self._plan_full_reads(
                pg, codec, acting, oid, lost, helpers, mine, my_shard,
                my_ver, target, size)
        return self._plan_subchunk_reads(
            pg, codec, acting, oid, lost, plan, helpers, mine, my_shard,
            my_ver, target, size)

    def _plan_full_reads(self, pg, codec, acting, oid, lost, helpers,
                         mine, my_shard, my_ver, target, size):
        """MDS plan: exactly the k planned full chunks feed the decode
        — reads/repaired lands at the textbook k, not n-1.  The local
        chunk joins the decode only when the PLAN names it (the default
        MDS plan picks the k lowest available shards, which may not
        include this primary's own) — it still anchors chunk_size,
        generation, and object size either way."""
        vers: dict[int, int | None] = {my_shard: my_ver}
        got = self._gather_chunks(
            pg, codec, acting, oid, set(helpers) - {my_shard},
            vers=vers, stray=False)
        if my_shard in helpers:
            got[my_shard] = mine
        if set(got) != set(helpers):
            return None  # a planned helper went silent: fall back
        for v in vers.values():
            if v is not None and v != target:
                if target is None:
                    target = v
                else:
                    return None  # mixed generations: fall back
        if any(len(b) != len(mine) for b in got.values()):
            return None
        chunks = {s: np.frombuffer(bytes(b), np.uint8)
                  for s, b in got.items()}
        try:
            dec = codec.decode({lost}, chunks, len(mine))
            out = np.asarray(dec[lost], np.uint8).tobytes()
        except Exception:
            return None
        nbytes = sum(len(b) for b in got.values())
        return out, size, len(got), nbytes

    def _plan_subchunk_reads(self, pg, codec, acting, oid, lost, plan,
                             helpers, mine, my_shard, my_ver, target,
                             size):
        """CLAY plan: fetch only the repair-plane sub-chunk ranges from
        each of the d helpers (ranged MECSubOpRead — hinfo-verified
        server-side) and rebuild through the codec's cached repair
        matrix: the live d/q-of-a-chunk repair bandwidth the bench
        measured offline, now on the recovery path."""
        if not hasattr(codec, "repair_matrix"):
            return None
        Z = codec.get_sub_chunk_count()
        chunk_size = len(mine)
        if Z <= 1 or chunk_size % Z:
            return None
        sub_len = chunk_size // Z
        nB = len(codec.repair_planes(lost))
        fetched: dict[int, np.ndarray] = {}
        bytes_read = 0
        for h in helpers:
            ranges = [tuple(r) for r in plan[h]]
            if ranges == [(0, -1)]:
                byte_ranges = [(0, chunk_size)]
            else:
                byte_ranges = [(off * sub_len, cnt * sub_len)
                               for off, cnt in ranges]
            want_len = sum(ln for _o, ln in byte_ranges)
            if h == my_shard:
                buf = b"".join(mine[o:o + ln] for o, ln in byte_ranges)
                ver = my_ver
            else:
                buf, ver = self._fetch_shard_ranges(
                    pg, acting, h, oid, byte_ranges)
            if buf is None or len(buf) != want_len:
                return None
            if ver is not None:
                if target is None:
                    target = ver
                elif ver != target:
                    return None  # stale-generation helper: fall back
            rows = np.frombuffer(buf, np.uint8).reshape(-1, sub_len)
            if rows.shape[0] not in (nB, Z):
                return None
            if rows.shape[0] == Z:
                # a full-chunk helper (want&avail merge case): slice
                # its repair planes for the stacked input
                rows = rows[np.asarray(codec.repair_planes(lost))]
            fetched[h] = rows
            bytes_read += want_len
        try:
            from ..ops.bitplane import apply_matrix_jax
            from ..ops.device_pool import POOL

            # cephdma: the cached repair matrix's stable digest keys the
            # device bitmatrix cache (no per-rebuild M.tobytes() host
            # copy), and the gathered helper sub-chunks commit to the
            # device through the stripe pool so repeated rebuilds of
            # one geometry recycle the same buffers
            if hasattr(codec, "repair_matrix_entry"):
                M, m_key = codec.repair_matrix_entry(lost, tuple(helpers))
            else:
                M, m_key = codec.repair_matrix(lost, tuple(helpers)), None
            x = np.concatenate([fetched[h] for h in helpers])
            x_dev = POOL.put(x) if POOL.enabled() else x
            try:
                out = np.asarray(apply_matrix_jax(M, x_dev, mat_key=m_key),
                                 np.uint8)
            finally:
                # the except below swallows apply failures into a None
                # result — the pooled sub-chunk buffer must still come
                # back or every failed rebuild shrinks the pool
                if x_dev is not x:
                    POOL.release(x_dev)
            chunk = out.reshape(Z * sub_len).tobytes()
        except Exception:
            return None
        return chunk, size, len(helpers), bytes_read

    def _fetch_shard_ranges(self, pg, acting, shard: int, oid: str,
                            byte_ranges: list[tuple[int, int]]):
        """(concatenated bytes of `byte_ranges` from one shard's stored
        chunk, that shard's per-object version) via one multi-range
        MECSubOpRead; (None, None) on any failure.  The serving side
        verifies the WHOLE chunk's hinfo before slicing
        (subops._handle_sub_read), so rot cannot ride a ranged read."""
        osd = acting[shard] if shard < len(acting) else -1
        if osd < 0 or not self.osdmap.is_up(osd):
            return None, None
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpRead(
                    tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                    offsets=[[o, ln] for o, ln in byte_ranges],
                    epoch=self.my_epoch(),
                )
            )
        except (OSError, ConnectionError):
            return None, None
        rep = self._wait_reply(tid)
        if rep is None or rep.retval != 0:
            return None, None
        return unpack_data(rep.data), getattr(rep, "ver", None)
