"""Per-PG state + shared OSD data-plane constants (reference:
src/osd/PG.h pg state, hobject naming, pg_log dup-op coverage).

Split out of osd/daemon.py (round-4 verdict item #6).
"""
from __future__ import annotations


import threading
from collections import OrderedDict

from ..common.lockdep import make_lock
from .pg_log import PGLog

class PGState:
    def __init__(self, pgid: str, pool_id: int, ps: int):
        self.pgid = pgid
        self.pool_id = pool_id
        self.ps = ps
        self.log = PGLog()
        self.version = 0
        # highest pool pg_num this PG has been split-scanned under (0 =
        # scan on next pass; in-memory: a restart just rescans)
        self.split_scanned = 0
        # live-snap-id tuple this PG was last trimmed against (None =
        # never trimmed; distinct from () = trimmed against empty set)
        self.snap_trimmed: tuple | None = None
        # epoch at which this PG's up/acting last CHANGED (reference:
        # pg_history_t::same_interval_since): sub-ops stamped with an
        # older epoch come from a primary of a PAST interval — a stale
        # primary racing a map change — and must be refused, or its
        # writes fork the PG's history behind the current interval's back
        self.interval_start = 0
        # interval this PG last completed its peering round in (phase 0
        # of _recover_pg: query peers, adopt the authoritative log).
        # A primary serves NO client ops until activated for the
        # CURRENT interval (reference: PG activation gates ops) — a
        # revived primary answering from its stale log/version would
        # fork history or falsely ack writes it cannot place.
        self.activated_interval = -1
        # formal history of CLOSED up/acting intervals (reference:
        # PastIntervals) — drives choose_acting's candidate pool, the
        # build_prior activation block, and bounded stray probing
        from .past_intervals import PastIntervals

        self.past_intervals = PastIntervals()
        # stray-location cache (reference: missing_loc): shard -> osd
        # that last answered a stray probe for this PG; lets a repeat
        # degraded read skip the probe wave.  In-memory only — a wrong
        # entry just costs one failed fetch and is dropped.
        self.stray_loc: dict[int, int] = {}
        # cumulative closures recorded this process-lifetime (observability
        # only — prune clears the history, not this)
        self.intervals_closed = 0
        # cephheal pg_stats (observability only): object-copies this
        # PG's LIVE peers were missing at the last recovery pass
        # (down/absent shards are counted live by _mgr_report from its
        # store walk); the push helpers decrement as objects land so a
        # long backfill drains visibly between passes
        self.stat_degraded_peers = 0
        # newest map epoch under which this PG logged a write (persisted
        # with the log): a revived OSD uses it as the starting point to
        # REBUILD interval history from the mon's old maps — intervals
        # that passed while it was down were never seen by _on_map
        # (reference: pg_history_t + build via past OSDMaps)
        self.last_map_epoch = 0
        self.intervals_rebuilt = False
        # shard collections known to hold this PG's meta locally (filled
        # by _load_pg_meta/_log_txn so _save_intervals never rescans the
        # whole store per map change)
        self.meta_cids: set[str] = set()
        # interval for which this primary last broadcast MPGClean
        self.clean_broadcast_interval = -1
        # reqid -> (retval, result) of COMPLETED mutations: a client
        # resend whose reply was lost is answered from here instead of
        # re-executed (reference: pg_log dup entries / osd_reqid_t);
        # success-only so retryable -EAGAIN refusals still re-execute
        self.reqid_cache: "OrderedDict[str, tuple]" = OrderedDict()
        # reqid -> Event of a mutation mid-execution: a resend racing the
        # original waits here instead of double-executing (reference:
        # PrimaryLogPG::check_in_progress_op)
        self.inflight: dict[str, threading.Event] = {}
        self.lock = make_lock("osd::pg")

    def meta_oid(self) -> str:
        return "_pgmeta"


# clone-object name separator (reference: clones are (oid, snapid) hobjects;
# here the snapid rides in the name, invisible to client listings)
CLONE_SEP = "\x02"

# client ops covered by reqid dup detection (mutations whose re-execution
# on a resend would be wrong or wasteful)
MUTATING_OPS = frozenset(
    {"write_full", "write", "append", "delete", "setxattr",
     "omap_set", "omap_rm", "omap_clear", "exec"}
)


def _current_generation(chunks: dict, vers: dict,
                        floor: int | None = None) -> dict:
    """Drop stale-GENERATION chunks: shards versioned below the newest
    version seen carry pre-RMW bytes that must never be mixed into a
    decode (None = wildcard, e.g. backfill-rebuilt).  `floor` is the
    LOG's newest data version for the object (when known): even if every
    reachable chunk is older — the current copies are on a crashed
    disk — the stale generation must read as MISSING, not as current,
    or a later splice-and-rewrite would launder the rollback into a
    fresh higher version (reference: the missing/unfound machinery)."""
    present = [v for v in vers.values() if v is not None]
    if floor is not None:
        present.append(floor)
    if not present:
        return chunks
    target = max(present)
    return {
        s: b for s, b in chunks.items()
        if vers.get(s) is None or vers.get(s) == target
    }


