"""ReadBatcher — the coalescing gather/decode layer behind `_ec_read`
(ROADMAP "Coalesced, device-resident READ plane"; the read-side twin of
osd/write_batcher.py).

arXiv:1709.05365's finding — that online-EC latency is dominated by the
queueing structure around the codec, not the GF math — applies
symmetrically to reads: a GET-heavy workload (RGW GETs, RBD boot
storms) used to walk the stack one op at a time, paying a per-op sub-op
fan-out for its chunk gather and, when degraded, a per-op
``apply_matrix_jax`` dispatch for its decode.  The batcher coalesces
both seams across concurrent ops:

- **Gather coalescing**: every shard-read a flush needs — `_ec_read`
  data-chunk gathers AND RMW old-byte range fetches — is grouped by
  (PG, shard, target OSD) and sent as ONE multi-oid ``MECSubOpRead``
  (the ``reads`` field generalizes PR-13's multi-range machinery), so a
  flush performs one sub-op fan-out no matter how many ops it carries.
  Replies are demuxed back per descriptor, and the per-entry semantics
  (``osd.ec.shard_read`` failpoint, hinfo CRC verify, stale-generation
  version echo) match the historical per-op path exactly.

- **Decode coalescing**: degraded stripes decode through the codec's
  CACHED decode matrix (``_decode_entry``), and all stripes of a flush
  sharing a matrix fuse along the byte-column axis into ONE pooled
  ``apply_matrix_jax`` dispatch — the input stacks commit through
  ``ops/device_pool.py`` (client reads now pool like recovery's
  ``decode_chunks`` already did), and per-op column windows are demuxed
  back bit-identically.  GF matrix application is byte-column-local
  (the same property the write batcher and the RMW parity delta rest
  on), so fusing changes scheduling, never bytes.

Flush policy mirrors the write batcher: size/byte caps
(``osd_read_batch_max_ops`` / ``osd_read_batch_max_bytes``) flush
immediately; an absolute window (``osd_read_batch_window_ms``) bounds
the first op's wait; an inter-arrival gap (window/8) flushes as soon as
arrivals stop.  Admission rides a ``Throttle`` sized at a few windows
of estimated bytes, so a saturated read plane blocks op threads at
admission and the stall propagates to the client's inflight budget.
Ops fall back to the historical inline path when coalescing is off
(window 0, stopped, a ``crash`` failpoint latched the batcher off) or
the backend sentinel has latched degraded — reads must keep flowing on
a sick accelerator, so a degraded sentinel bypasses the batch plane
entirely rather than trusting a pooled decode.

Fault injection: ``osd.read_batcher.gather`` fires at the head of every
flush.  ``error`` fails EVERY op in the batch (each re-runs inline or
surfaces EIO upstream — no wrong bytes are ever served); ``delay(s)``
stalls the flush; ``crash`` additionally latches coalescing off.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..common.failpoint import FailpointCrash, failpoint
from ..common.kernel_telemetry import SENTINEL, TELEMETRY
from ..common.lockdep import make_lock
from ..common.throttle import Throttle
from ..common.tracer import TRACER, op_trace, trace_now
from .messages import unpack_data


class ReadReq:
    """One shard-read descriptor: acting-slot `shard`, object `oid`,
    and an optional byte range (off None = whole chunk)."""

    __slots__ = ("shard", "oid", "off", "ln")

    def __init__(self, shard: int, oid: str,
                 off: int | None = None, ln: int | None = None):
        self.shard = shard
        self.oid = oid
        self.off = off
        self.ln = ln


class _PendingRead:
    """One queued op: either a `gather` (a list of `ReadReq`s against
    one PG's acting set) or a `decode` (a [rows, W] stack to multiply
    through a cached decode matrix).  `results` is the demuxed payload:
    gather -> {req index: (bytes, ver, size) | None}, decode -> the
    [k, W] decoded array."""

    __slots__ = ("kind", "pgid", "acting", "reqs", "dm", "dm_key",
                 "stack", "nbytes", "arrival", "event", "results",
                 "error", "admitted", "tctx", "tracked", "acct",
                 "queued_at")

    def __init__(self, kind: str, nbytes: int):
        self.kind = kind
        self.pgid = None
        self.acting = None
        self.reqs: list[ReadReq] = []
        self.dm = None
        self.dm_key = None
        self.stack = None
        self.nbytes = nbytes
        self.arrival = time.monotonic()
        self.event = threading.Event()
        self.results = None
        self.error: BaseException | None = None
        self.admitted = False
        self.tctx = None
        self.tracked = None
        self.acct = None
        self.queued_at = 0.0


class ReadBatcher:
    """Gather/decode coalescer (see module docstring).

    `io` is the transport/store adapter the flusher drives — the OSD
    itself in the daemon (ECBackendMixin's ``rb_*`` methods), a local
    fake in bench/tests:

    - ``rb_local_osd() -> int``
    - ``rb_is_up(osd) -> bool``
    - ``rb_read_local(pgid, shard, oid, off, ln) -> (bytes|None, ver, size)``
    - ``rb_send_multiread(osd, pgid, shard, reads, epoch) -> tid | None``
    - ``rb_wait_multireads(tids, deadline) -> {tid: reply}``
    - ``rb_epoch() -> int``
    - ``rb_reply_timeout() -> float``
    """

    #: admission throttle holds this many byte-caps of queued work
    QUEUE_WINDOWS = 4
    #: ceiling on one op's wait for admission into a saturated queue
    ADMIT_TIMEOUT = 30.0
    #: ceiling on one op's wait for its flush (window + fan-out + decode)
    OP_TIMEOUT = 60.0

    def __init__(self, cct, io, logger=None, entity: str = ""):
        self._cct = cct
        self._io = io
        self._logger = logger
        self._entity = entity or (cct.name if cct is not None else "")
        self._lock = make_lock("osd::read_batcher")
        self._cond = threading.Condition(self._lock)
        self._queue: list[_PendingRead] = []
        self._queued_bytes = 0
        self._flush_asap = False
        self._stop_flag = False
        self._crashed = False
        self._thread: threading.Thread | None = None
        self._admission = Throttle(
            "read_batcher::queue",
            self._max_bytes() * self.QUEUE_WINDOWS,
        )
        self._stats = {"flushes": 0, "ops": 0, "bytes": 0, "inline": 0,
                       "fanouts": 0, "decode_groups": 0}

    # -- config (runtime-changeable: read per use) -------------------------
    def _window(self) -> float:
        if self._cct is None:
            return 0.0
        return max(
            0.0, float(self._cct.conf.get("osd_read_batch_window_ms"))) / 1e3

    def _max_ops(self) -> int:
        if self._cct is None:
            return 1
        return max(1, int(self._cct.conf.get("osd_read_batch_max_ops")))

    def _max_bytes(self) -> int:
        if self._cct is None:
            return 0
        return max(0, int(self._cct.conf.get("osd_read_batch_max_bytes")))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._thread is not None:
                return
            self._stop_flag = False
            self._thread = threading.Thread(
                target=self._flush_loop,
                name=f"{self._entity}-rb-flush", daemon=True,
            )
        self._thread.start()

    def stop(self) -> None:
        """Drain-and-stop: queued ops are flushed (shutdown flush), then
        the flusher exits; later submits run inline."""
        with self._cond:
            self._stop_flag = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)

    def coalescing(self) -> bool:
        """True when submits will be batched rather than run inline.
        A degraded backend sentinel bypasses the batch plane: reads
        must keep flowing on a sick accelerator, so every op takes the
        historical per-op path until the sentinel clears."""
        with self._lock:
            return (self._thread is not None and not self._stop_flag
                    and not self._crashed) and self._window() > 0.0 \
                and not SENTINEL.is_degraded

    # -- introspection (tests / bench) -------------------------------------
    @property
    def admission(self) -> Throttle:
        return self._admission

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def flush_now(self) -> None:
        """Force the current queue out without waiting for window/caps."""
        with self._cond:
            self._flush_asap = True
            self._cond.notify_all()

    def _use_pool(self) -> bool:
        from ..ops.device_pool import POOL

        if self._cct is not None \
                and not bool(self._cct.conf.get("ec_device_pool")):
            return False
        return POOL.enabled()

    # -- submit: gathers ---------------------------------------------------
    def gather(self, pgid, acting, reqs: list[ReadReq],
               est_bytes: int) -> dict:
        """Blocking convenience: coalesced shard gather for one op.
        Returns {req index: (bytes, ver, size) | None} — None rows are
        missing/EIO/timed-out shards, exactly as the per-op path skips
        them."""
        return self.gather_wait(self.gather_submit(pgid, acting, reqs,
                                                   est_bytes))

    def gather_submit(self, pgid, acting, reqs: list[ReadReq],
                      est_bytes: int) -> _PendingRead:
        """Queue one op's shard-read descriptors and return its ticket
        (every ticket MUST be passed to gather_wait — it holds admission
        budget until then).  `est_bytes`: the caller's byte estimate
        (sum of ranged lengths / k x chunk-size) for throttle sizing —
        an estimate is fine, backpressure only needs proportionality."""
        p = _PendingRead("gather", max(1, int(est_bytes)))
        p.pgid = pgid
        p.acting = list(acting)
        p.reqs = list(reqs)
        return self._submit(p)

    def gather_wait(self, p: _PendingRead) -> dict:
        return self._wait(p)

    # -- submit: decodes ---------------------------------------------------
    def decode(self, dm: np.ndarray, stack: np.ndarray,
               dm_key: str | None = None) -> np.ndarray:
        """Blocking convenience: [rows, W] surviving-chunk stack in,
        [k, W] decoded data out, bit-identical to
        ``apply_matrix_jax(dm, stack)``; all decodes of a flush sharing
        `dm` fuse into one pooled dispatch."""
        return self.decode_wait(self.decode_submit(dm, stack, dm_key))

    def decode_submit(self, dm: np.ndarray, stack: np.ndarray,
                      dm_key: str | None = None) -> _PendingRead:
        stack = np.ascontiguousarray(stack, dtype=np.uint8)
        p = _PendingRead("decode", stack.nbytes)
        p.dm = np.ascontiguousarray(dm, dtype=np.uint8)
        p.dm_key = dm_key
        p.stack = stack
        return self._submit(p)

    def decode_wait(self, p: _PendingRead) -> np.ndarray:
        return self._wait(p)

    # -- submit plumbing ---------------------------------------------------
    def _submit(self, p: _PendingRead) -> _PendingRead:
        st = op_trace()
        if st is not None:
            if TRACER.enabled:
                p.tctx = st.get("ctx")
            p.tracked = st.get("tracked")
            p.acct = st.get("acct")
        if not self.coalescing():
            self._run_inline(p)
            return p
        # backpressure: block HERE, at admission — the op thread's
        # upstream inflight budget carries the stall to the client
        cap = self._max_bytes() * self.QUEUE_WINDOWS
        if cap != self._admission.max:
            self._admission.reset_max(cap)
        t_adm0 = trace_now()
        if not self._admission.get(p.nbytes, timeout=self.ADMIT_TIMEOUT):
            raise IOError(
                f"read batcher admission timed out "
                f"({self._admission.current} B queued, cap {cap} B)"
            )
        p.admitted = True
        try:
            t_adm1 = trace_now()
            if p.acct is not None:
                tab, client, pool = p.acct
                tab.record_stage(client, pool, "admission",
                                 t_adm1 - t_adm0)
            if p.tracked is not None:
                p.tracked.stage_add("admission", t_adm1 - t_adm0)
            if p.tctx is not None:
                TRACER.record(p.tctx, "admission", entity=self._entity,
                              t0=t_adm0, t1=t_adm1, nbytes=p.nbytes)
            p.queued_at = t_adm1
            enqueued = False
            with self._cond:
                if not (self._stop_flag or self._crashed):
                    enqueued = True
                    self._queue.append(p)
                    self._queued_bytes += p.nbytes
                    # only the flusher waits on the shared condition;
                    # per-op completion rides p.event (no herd)
                    self._cond.notify_all()
            if not enqueued:  # raced a stop/crash: run inline
                self._run_inline(p)
            return p
        except Exception:
            # nobody will _wait() on a ticket whose submit raised —
            # hand the admission slot back before escaping, or the
            # throttle pins at its cap under sustained errors
            p.admitted = False
            self._admission.put(p.nbytes)
            raise

    def _wait(self, p: _PendingRead):
        try:
            if not p.event.wait(timeout=self.OP_TIMEOUT):
                raise TimeoutError(
                    f"read batcher flush of {p.nbytes} B {p.kind} timed "
                    f"out after {self.OP_TIMEOUT}s"
                )
            if p.error is not None:
                raise p.error
            return p.results
        finally:
            if p.admitted:
                p.admitted = False
                self._admission.put(p.nbytes)

    # -- inline fallback ---------------------------------------------------
    def _run_inline(self, p: _PendingRead) -> None:
        """Historical per-op path, on the submitting thread: a gather
        fans out alone, a decode is one solo pooled dispatch.  Also the
        recovery path for ops a flush failpoint erred out — bytes from
        here are the referee the batched path must match."""
        with self._lock:
            self._stats["inline"] += 1
        if self._logger is not None:
            self._logger.inc("read_batcher_inline")
        try:
            if p.kind == "gather":
                self._run_gathers([p])
            else:
                self._run_decodes([p])
        except Exception as e:
            p.error = e
        p.event.set()

    # -- flusher -----------------------------------------------------------
    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop_flag:
                    self._cond.wait(timeout=0.5)
                if not self._queue:
                    return  # stopped and drained
                self._wait_for_batch_locked()
                batch = self._queue
                self._queue = []
                self._queued_bytes = 0
                self._flush_asap = False
            try:
                self._flush_batch(batch)
            except Exception as e:  # belt: the flusher must never die
                if self._cct is not None:
                    self._cct.dout("osd", 0,
                                   f"{self._entity} read batcher flush "
                                   f"raised: {e!r}")
                self._complete(batch, err=e)

    def _wait_for_batch_locked(self) -> None:
        """Coalescing wait (lock held): returns once the batch should
        flush — caps reached, absolute window expired, an inter-arrival
        gap passed with no growth, or stop/flush_now."""
        window = self._window()
        max_ops = self._max_ops()
        max_bytes = self._max_bytes()
        first = self._queue[0].arrival
        gap = max(window / 8.0, 5e-5)
        while (
            not self._stop_flag
            and not self._flush_asap
            and len(self._queue) < max_ops
            and (max_bytes <= 0 or self._queued_bytes < max_bytes)
        ):
            remain = first + window - time.monotonic()
            if remain <= 0:
                break
            n0 = len(self._queue)
            self._cond.wait(timeout=min(remain, gap))
            if len(self._queue) == n0:
                break  # quiescent: every in-flight reader already queued

    def _flush_batch(self, batch: list[_PendingRead]) -> None:
        t0 = time.perf_counter()
        w0 = trace_now()
        for p in batch:
            if not p.queued_at:
                continue
            q_dur = max(0.0, w0 - p.queued_at)
            if p.acct is not None:
                tab, client, pool = p.acct
                tab.record_stage(client, pool, "queue", q_dur)
            if p.tracked is not None:
                p.tracked.stage_add("queue", q_dur)
            if p.tctx is not None:
                TRACER.record(p.tctx, "queue", entity=self._entity,
                              t0=p.queued_at, t1=w0)
        err: BaseException | None = None
        try:
            failpoint("osd.read_batcher.gather", cct=self._cct,
                      entity=self._entity, ops=len(batch))
        except FailpointCrash as e:
            # simulated death of the read plane: fail the batch and
            # latch coalescing off — later submits run inline
            with self._cond:
                self._crashed = True
            err = e
        except Exception as e:
            err = e
        if err is None:
            gathers = [p for p in batch if p.kind == "gather"]
            decodes = [p for p in batch if p.kind == "decode"]
            try:
                if gathers:
                    g0 = trace_now()
                    self._run_gathers(gathers)
                    if self._logger is not None:
                        self._logger.hinc("stage_read_gather",
                                          trace_now() - g0)
                if decodes:
                    d0 = trace_now()
                    self._run_decodes(decodes)
                    if self._logger is not None:
                        self._logger.hinc("stage_read_decode",
                                          trace_now() - d0)
            except Exception as e:
                err = e
        w1 = trace_now()
        if err is None:
            for p in batch:
                if p.tctx is not None:
                    TRACER.record(p.tctx, "read_flush",
                                  entity=self._entity, t0=w0, t1=w1,
                                  ops=len(batch))
        self._complete(batch, err=err)
        if err is None:
            nbytes = sum(p.nbytes for p in batch)
            with self._lock:
                self._stats["flushes"] += 1
                self._stats["ops"] += len(batch)
                self._stats["bytes"] += nbytes
            if self._logger is not None:
                self._logger.inc("read_batcher_flushes")
                self._logger.inc("read_batcher_ops", len(batch))
                self._logger.inc("read_batcher_bytes", nbytes)
                self._logger.tinc("read_batcher_flush_latency",
                                  time.perf_counter() - t0)

    # -- gather execution --------------------------------------------------
    def _run_gathers(self, gathers: list[_PendingRead]) -> None:
        """One sub-op fan-out for EVERY descriptor of every gather op:
        local reads served from the store, remote reads grouped by
        (pgid, shard, osd) into one multi-oid ``MECSubOpRead`` each,
        collected under one shared deadline."""
        io = self._io
        local = io.rb_local_osd()
        for p in gathers:
            p.results = {}
        # (pgid, shard, osd) -> (send rows, [(op, req index), ...])
        remote: dict[tuple, tuple[list, list]] = {}
        for p in gathers:
            for i, r in enumerate(p.reqs):
                osd = p.acting[r.shard] if r.shard < len(p.acting) else -1
                if osd == local:
                    p.results[i] = io.rb_read_local(
                        p.pgid, r.shard, r.oid, r.off, r.ln)
                    continue
                if osd < 0 or not io.rb_is_up(osd):
                    p.results[i] = None
                    continue
                rows, owners = remote.setdefault(
                    (p.pgid, r.shard, osd), ([], []))
                rows.append([r.oid, r.off, r.ln])
                owners.append((p, i))
        if not remote:
            return
        tids: dict[int, tuple] = {}
        epoch = io.rb_epoch()
        for (pgid, shard, osd), (rows, owners) in remote.items():
            tid = io.rb_send_multiread(osd, pgid, shard, rows, epoch)
            if tid is None:
                for p, i in owners:
                    p.results[i] = None
                continue
            tids[tid] = (pgid, shard, osd)
        with self._lock:
            self._stats["fanouts"] += len(tids)
        deadline = time.monotonic() + io.rb_reply_timeout()
        replies = io.rb_wait_multireads(set(tids), deadline)
        for tid, key in tids.items():
            _rows, owners = remote[key]
            rep = replies.get(tid)
            res = getattr(rep, "results", None) if rep is not None else None
            for j, (p, i) in enumerate(owners):
                row = res[j] if res is not None and j < len(res) else None
                if row is None or row[0] != 0:
                    p.results[i] = None
                else:
                    p.results[i] = (
                        unpack_data(row[1]),
                        row[3],
                        int(row[2]) if row[2] is not None else None,
                    )

    # -- decode execution --------------------------------------------------
    def _run_decodes(self, decodes: list[_PendingRead]) -> None:
        """One fused pack -> pooled apply -> demux per decode-matrix
        group.  Stacks sharing a matrix concat along the column axis
        (variable widths are fine — demux walks cumulative offsets);
        the packed stack commits through the device pool and the single
        ``np.asarray`` per group is the deliberate reply-serialization
        sync — decoded bytes go straight into a client reply, there is
        nothing downstream to keep device-resident for."""
        from ..ops.bitplane import apply_matrix_jax, current_backend
        from ..ops.device_pool import POOL

        groups: dict[object, list[_PendingRead]] = {}
        for p in decodes:
            key = p.dm_key if p.dm_key is not None else p.dm.tobytes()
            groups.setdefault((key, p.stack.shape[0]), []).append(p)
        use_pool = self._use_pool()
        t0 = time.perf_counter()
        bytes_in = 0
        host_copy = 0
        for ps in groups.values():
            dm = ps[0].dm
            packed = (ps[0].stack if len(ps) == 1 else
                      np.concatenate([p.stack for p in ps], axis=1))
            if len(ps) > 1:
                host_copy += packed.nbytes
            bytes_in += packed.nbytes
            dev = POOL.put(packed) if use_pool else packed
            try:
                out = np.asarray(  # noqa: CL8 — the decoded bytes serialize into client replies; this is the one deliberate read-plane sync
                    apply_matrix_jax(dm, dev, mat_key=ps[0].dm_key),
                    dtype=np.uint8)
            finally:
                if dev is not packed:
                    POOL.release(dev)
            host_copy += out.nbytes
            c = 0
            for p in ps:
                w = p.stack.shape[1]
                p.results = out[:, c:c + w]
                c += w
        with self._lock:
            self._stats["decode_groups"] += len(groups)
        if TELEMETRY.enabled:
            TELEMETRY.record(
                "read_batch_decode", current_backend(),
                time.perf_counter() - t0, bytes_in=bytes_in,
                bytes_out=sum(int(p.results.nbytes) for p in decodes),
                synced=True, host_copy_bytes=host_copy)

    def _complete(self, batch: list[_PendingRead],
                  err: BaseException | None = None) -> None:
        for p in batch:
            if err is not None:
                p.error = err
            p.event.set()
