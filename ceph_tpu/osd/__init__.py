"""OSDMap-layer placement: pool→PG→OSD mapping and the upmap balancer.

TPU-native rebuild of the placement half of the reference's src/osd layer
(SURVEY.md §2.3 OSDMap row, §2.5 balancer row).  The daemon half (OSD boot,
peering, PrimaryLogPG) is process machinery the north star leaves untouched;
what lives here is the pure placement math every client and the mgr balancer
run: OSDMap::pg_to_up_acting_osds and OSDMap::calc_pg_upmaps, with the
CRUSH descent batched on TPU (crush_do_rule_batch).
"""
from .osdmap import (
    PG_POOL_ERASURE,
    PG_POOL_REPLICATED,
    OSDMap,
    PGPool,
    ceph_stable_mod,
    pg_num_mask,
)
from .balancer import calc_pg_upmaps
from .placement import (
    cluster_report,
    diff_mappings,
    pool_pg_counts,
    pool_skew,
    rule_osd_info,
)

__all__ = [
    "OSDMap",
    "PGPool",
    "PG_POOL_ERASURE",
    "PG_POOL_REPLICATED",
    "calc_pg_upmaps",
    "ceph_stable_mod",  # noqa: CL12 — exported helper name, not a series
    "cluster_report",
    "diff_mappings",
    "pg_num_mask",
    "pool_pg_counts",
    "pool_skew",
    "rule_osd_info",
]
