"""cephplace — the placement scoring core on batched CRUSH.

Reference: the distribution math behind `ceph osd df` (PGMap's per-OSD
PG counts vs weight share), osdmaptool `--test-map-pgs`, and the mgr
balancer's `eval` score (src/pybind/mgr/balancer/module.py ::
Eval/calc_stats) — collapsed into ONE pure implementation shared by
every consumer (the mgr placement module, the balancer, `ceph osd df`,
and osdmaptool), so the three surfaces can never disagree about what
"skewed" means.

Everything here is pure map arithmetic over batched mappings: the CRUSH
descent itself runs as `OSDMap.map_pool` → `crush_do_rule_batch` (ONE
device launch per pool, visible in kernel telemetry), and this module
only does vectorized host post-passes on the resulting [pg_num, size]
arrays — the same split SURVEY.md §3.3 prescribes for batch consumers.

Three product families:

- **counts**: per-OSD PG-shard and primary counts from a mapping
  (`shard_counts`, `pool_pg_counts`);
- **skew**: weight-proportional ideal shares and deviation metrics
  (`ideal_targets`, `skew_metrics`, `pool_skew`, `cluster_report`) —
  ``max_deviation``/``stddev`` are in PG shards, ``score`` is the
  stddev normalized by the mean ideal share (0 = perfectly balanced,
  dimensionless so pools of different sizes compare);
- **diff**: epoch-over-epoch remap forecasting (`diff_mappings`) — PGs
  and shards whose placement changed between two device-batched
  mappings, with predicted bytes-to-move when per-shard byte weights
  are supplied (the mgr derives them from pool stats).
"""
from __future__ import annotations

import numpy as np

from ..crush.types import RuleOp
from .osdmap import OSDMap, PG_POOL_ERASURE


def _rule_take_and_type(osdmap: OSDMap, rule_id: int) -> tuple[int, int]:
    """Extract (take root, failure-domain type) from a simple rule chain."""
    root, ftype = None, 0
    for st in osdmap.crush.map.rules[rule_id].steps:
        if st.op == RuleOp.TAKE:
            root = st.arg1
        elif st.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            ftype = st.arg2
    if root is None:
        raise ValueError(f"rule {rule_id} has no TAKE step")
    return root, ftype


def rule_osd_info(
    osdmap: OSDMap, rule_id: int
) -> tuple[np.ndarray, dict[int, int]]:
    """Per-OSD CRUSH weight and failure-domain id for one rule's subtree.

    reference: OSDMap::get_rule_weight_osd_map (weights) plus the subtree
    walk calc_pg_upmaps does to group candidates by failure domain."""
    root, ftype = _rule_take_and_type(osdmap, rule_id)
    weights = np.zeros(osdmap.max_osd, dtype=np.float64)
    for osd, w in osdmap.crush.get_rule_weight_osd_map(rule_id).items():
        if osd < osdmap.max_osd:
            weights[osd] = w
    domain: dict[int, int] = {}

    def walk(bid: int, dom: int | None) -> None:
        b = osdmap.crush.map.buckets[bid]
        here = bid if b.type == ftype else dom
        for it in b.items:
            if it >= 0:
                domain[it] = it if ftype == 0 else (here if here is not None else it)
            else:
                walk(it, here)

    walk(root, None)
    # an out (reweight 0) OSD takes no PGs — exclude from the target share
    for o in range(osdmap.max_osd):
        if osdmap.osd_weight[o] == 0 or not osdmap.is_up(o):
            weights[o] = 0.0
    return weights, domain


def shard_counts(mapping, max_osd: int) -> np.ndarray:
    """Per-OSD shard count over one mapping array (up [pg_num, size] or
    primaries [pg_num]); ITEM_NONE holes don't count."""
    counts = np.zeros(max_osd, dtype=np.int64)
    arr = np.asarray(mapping)
    valid = arr[(arr >= 0) & (arr < max_osd)]
    if valid.size:
        ids, c = np.unique(valid, return_counts=True)
        counts[ids] += c
    return counts


def pool_pg_counts(osdmap: OSDMap, pools=None) -> np.ndarray:
    """PG-shard count per OSD over the given pools (batched CRUSH path)."""
    counts = np.zeros(osdmap.max_osd, dtype=np.int64)
    for pid in pools if pools is not None else sorted(osdmap.pools):
        up, _ = osdmap.map_pool(pid)
        counts += shard_counts(up, osdmap.max_osd)
    return counts


def ideal_targets(weights: np.ndarray, total_shards: int) -> np.ndarray:
    """Weight-proportional ideal shard share per OSD (reference: the
    `target` term of calc_pg_upmaps / balancer eval).  Zero-weight
    (out/down) OSDs get target 0."""
    total_w = float(np.asarray(weights).sum())
    if total_w <= 0:
        return np.zeros(len(weights), dtype=np.float64)
    return np.asarray(weights, dtype=np.float64) / total_w * float(total_shards)


def skew_metrics(counts: np.ndarray, target: np.ndarray,
                 eligible: np.ndarray) -> dict:
    """Deviation metrics over the eligible (weight > 0) OSDs:
    ``max_deviation``/``stddev`` in PG shards, ``score`` = stddev
    normalized by the mean ideal share (0 = perfect)."""
    eligible = np.asarray(eligible, dtype=bool)
    if not eligible.any():
        return {"max_deviation": 0.0, "stddev": 0.0, "score": 0.0}
    d = np.asarray(counts, dtype=np.float64)[eligible] \
        - np.asarray(target, dtype=np.float64)[eligible]
    mean_t = float(np.asarray(target, dtype=np.float64)[eligible].mean())
    stddev = float(np.sqrt((d * d).mean()))
    return {
        "max_deviation": float(np.abs(d).max()),
        "stddev": stddev,
        "score": stddev / max(1.0, mean_t),
    }


def pool_skew(osdmap: OSDMap, pool_id: int, up=None) -> dict:
    """One pool's distribution report: per-OSD counts vs the
    weight-proportional ideal plus the skew metrics.  `up` accepts a
    precomputed `map_pool` result so one batched scan feeds every
    consumer (the mgr module computes mappings once per epoch)."""
    pool = osdmap.pools[pool_id]
    if up is None:
        up, _ = osdmap.map_pool(pool_id)
    weights, _dom = rule_osd_info(osdmap, pool.crush_rule)
    counts = shard_counts(up, osdmap.max_osd)
    placed = int((np.asarray(up) >= 0).sum())
    target = ideal_targets(weights, placed)
    eligible = weights > 0
    return {
        "pool": pool_id,
        "name": pool.name,
        "pg_num": pool.pg_num,
        "size": pool.size,
        "shards": placed,
        "counts": counts,
        "target": target,
        "eligible": eligible,
        **skew_metrics(counts, target, eligible),
    }


def cluster_report(osdmap: OSDMap, pools=None, mappings=None) -> dict:
    """Full-cluster distribution report: per-pool skew + aggregated
    per-OSD counts/targets/primaries + cluster-level metrics.

    `mappings` is an optional {pool_id: (up, primaries)} of precomputed
    `map_pool` results; absent pools are mapped here (each one batched
    CRUSH launch)."""
    pids = list(pools) if pools is not None else sorted(osdmap.pools)
    per_pool: dict[int, dict] = {}
    counts = np.zeros(osdmap.max_osd, dtype=np.int64)
    primaries = np.zeros(osdmap.max_osd, dtype=np.int64)
    targets = np.zeros(osdmap.max_osd, dtype=np.float64)
    eligible = np.zeros(osdmap.max_osd, dtype=bool)
    for pid in pids:
        if mappings is not None and pid in mappings:
            up, prim = mappings[pid]
        else:
            up, prim = osdmap.map_pool(pid)
        sk = pool_skew(osdmap, pid, up=up)
        per_pool[pid] = sk
        counts += sk["counts"]
        targets += sk["target"]
        eligible |= sk["eligible"]
        primaries += shard_counts(prim, osdmap.max_osd)
    return {
        "epoch": osdmap.epoch,
        "pools": per_pool,
        "osd_counts": counts,
        "osd_primaries": primaries,
        "osd_targets": targets,
        "eligible": eligible,
        **skew_metrics(counts, targets, eligible),
    }


def diff_mappings(osdmap: OSDMap, prev: dict, cur: dict,
                  shard_bytes: dict | None = None) -> dict:
    """Epoch-over-epoch remap forecast from two batched mappings.

    `prev`/`cur` are {pool_id: up [pg_num, size]} from the old and new
    maps.  A shard is REMAPPED when its current slot holds an OSD the
    PG's previous placement did not (positional for EC — shard identity
    is positional; set-membership for replicated — the up list compacts
    and reorders freely).  Shards landing in a -1 hole are degraded,
    not misplaced, and don't count.  `shard_bytes` maps pool_id to the
    average bytes one shard carries (the mgr derives it from reported
    pool stats) for the predicted-bytes-to-move forecast."""
    shard_bytes = shard_bytes or {}
    per_pool: dict[int, dict] = {}
    tot_pgs = tot_shards = 0
    total_shards_cur = 0
    predicted = 0.0
    for pid in sorted(set(cur)):
        b = np.asarray(cur[pid])
        total_shards_cur += int((b >= 0).sum())
    for pid in sorted(set(prev) & set(cur)):
        pool = osdmap.pools.get(pid)
        a = np.asarray(prev[pid])
        b = np.asarray(cur[pid])
        if pool is None:
            continue
        if a.shape != b.shape:
            # pg_num/size changed (split): every currently-placed shard
            # is potentially moving — count them all, flagged
            moved_per_pg = (b >= 0).sum(axis=1)
            resized = True
        elif pool.type == PG_POOL_ERASURE:
            moved_per_pg = ((a != b) & (b >= 0)).sum(axis=1)
            resized = False
        else:
            # replicated: membership, not position (the up list compacts)
            member = (b[:, :, None] == a[:, None, :]).any(axis=2)
            moved_per_pg = (~member & (b >= 0)).sum(axis=1)
            resized = False
        pgs_moved = int((moved_per_pg > 0).sum())
        shards_moved = int(moved_per_pg.sum())
        if not pgs_moved:
            continue
        pool_bytes = float(shard_bytes.get(pid, 0.0)) * shards_moved
        per_pool[pid] = {
            "name": pool.name,
            "pg_num": int(b.shape[0]),
            "pgs_remapped": pgs_moved,
            "shards_remapped": shards_moved,
            "predicted_bytes": int(pool_bytes),
            "resized": resized,
        }
        tot_pgs += pgs_moved
        tot_shards += shards_moved
        predicted += pool_bytes
    return {
        "pools": per_pool,
        "pgs_remapped": tot_pgs,
        "shards_remapped": tot_shards,
        "total_shards": total_shards_cur,
        "misplaced_fraction": (tot_shards / total_shards_cur
                               if total_shards_cur else 0.0),
        "predicted_bytes": int(predicted),
        "pools_added": sorted(set(cur) - set(prev)),
        "pools_removed": sorted(set(prev) - set(cur)),
    }


def osd_rows(report: dict, osdmap: OSDMap) -> list[dict]:
    """Flatten a cluster_report into JSON-safe per-OSD rows — the shape
    `ceph osd df`'s deviation columns and the mgr's ceph_placement_*
    per-OSD series both consume (one implementation, every consumer)."""
    rows = []
    counts = report["osd_counts"]
    prims = report["osd_primaries"]
    targets = report["osd_targets"]
    eligible = report["eligible"]
    # bound by the report's arrays: a map whose max_osd grew since the
    # report was scanned must not index past them (new OSDs get rows
    # once a scan covers them)
    for o in range(min(osdmap.max_osd, len(counts))):
        if not osdmap.exists(o):
            continue
        rows.append({
            "osd": o,
            "shards": int(counts[o]),
            "primaries": int(prims[o]),
            "target": round(float(targets[o]), 2),
            "deviation": round(float(counts[o] - targets[o]), 2),
            "eligible": bool(eligible[o]),
        })
    return rows
