"""Upmap balancer — the calc_pg_upmaps optimization loop on batched CRUSH.

Reference: src/osd/OSDMap.cc :: OSDMap::calc_pg_upmaps, driven by the mgr
balancer module (src/pybind/mgr/balancer/module.py, upmap mode): clone the
map, find over/underfull OSDs vs their weight-proportional PG share, and
emit pg_upmap_items entries moving PG shards from the fullest OSD to the
emptiest one that keeps the placement valid (same eligible device set,
distinct failure domains).  This is SURVEY.md §3.3's flagship batch-CRUSH
consumer: the full pool map runs as ONE crush_do_rule_batch launch on TPU,
and the greedy loop then only does sparse host-side bookkeeping — upmap
overrides never change the raw CRUSH output, so counts update incrementally
without re-descending.

The reference's loop additionally retries candidate deviations in a few
stochastic orders; this implementation is deterministic greedy (largest
deviation first), which the tests exploit for stable golden behavior.
"""
from __future__ import annotations

import numpy as np

from ..crush.types import RuleOp
from .osdmap import OSDMap


def _rule_take_and_type(osdmap: OSDMap, rule_id: int) -> tuple[int, int]:
    """Extract (take root, failure-domain type) from a simple rule chain."""
    root, ftype = None, 0
    for st in osdmap.crush.map.rules[rule_id].steps:
        if st.op == RuleOp.TAKE:
            root = st.arg1
        elif st.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            ftype = st.arg2
    if root is None:
        raise ValueError(f"rule {rule_id} has no TAKE step")
    return root, ftype


def rule_osd_info(
    osdmap: OSDMap, rule_id: int
) -> tuple[np.ndarray, dict[int, int]]:
    """Per-OSD CRUSH weight and failure-domain id for one rule's subtree.

    reference: OSDMap::get_rule_weight_osd_map (weights) plus the subtree
    walk calc_pg_upmaps does to group candidates by failure domain."""
    root, ftype = _rule_take_and_type(osdmap, rule_id)
    weights = np.zeros(osdmap.max_osd, dtype=np.float64)
    for osd, w in osdmap.crush.get_rule_weight_osd_map(rule_id).items():
        if osd < osdmap.max_osd:
            weights[osd] = w
    domain: dict[int, int] = {}

    def walk(bid: int, dom: int | None) -> None:
        b = osdmap.crush.map.buckets[bid]
        here = bid if b.type == ftype else dom
        for it in b.items:
            if it >= 0:
                domain[it] = it if ftype == 0 else (here if here is not None else it)
            else:
                walk(it, here)

    walk(root, None)
    # an out (reweight 0) OSD takes no PGs — exclude from the target share
    for o in range(osdmap.max_osd):
        if osdmap.osd_weight[o] == 0 or not osdmap.is_up(o):
            weights[o] = 0.0
    return weights, domain


def pool_pg_counts(osdmap: OSDMap, pools=None) -> np.ndarray:
    """PG-shard count per OSD over the given pools (batched CRUSH path)."""
    counts = np.zeros(osdmap.max_osd, dtype=np.int64)
    for pid in pools if pools is not None else sorted(osdmap.pools):
        up, _ = osdmap.map_pool(pid)
        ids, c = np.unique(up[up >= 0], return_counts=True)
        counts[ids] += c
    return counts


def calc_pg_upmaps(
    osdmap: OSDMap,
    max_deviation: float = 1.0,
    max_iterations: int = 100,
    pools=None,
) -> list[tuple[int, int, int, int]]:
    """Greedy upmap balance; mutates osdmap.pg_upmap_items.

    Returns the applied changes as (pool, ps, from_osd, to_osd) tuples —
    the analog of the incremental OSDMap::calc_pg_upmaps fills for the mgr
    balancer to commit.  max_deviation is in PG shards, as in the reference
    (osd_calc_pg_upmaps_max_deviation, default 1 → perfectly tight)."""
    changes: list[tuple[int, int, int, int]] = []
    for pid in pools if pools is not None else sorted(osdmap.pools):
        pool = osdmap.pools[pid]
        weights, domain = rule_osd_info(osdmap, pool.crush_rule)
        total_w = weights.sum()
        if total_w <= 0:
            continue
        up, _ = osdmap.map_pool(pid)
        rows = [list(r) for r in up]
        counts = np.zeros(osdmap.max_osd, dtype=np.float64)
        ids, c = np.unique(up[up >= 0], return_counts=True)
        counts[ids] += c
        shards = sum(1 for r in rows for o in r if o >= 0)
        target = weights / total_w * shards
        eligible = weights > 0

        for _ in range(max_iterations):
            dev = np.where(eligible, counts - target, -np.inf)
            o_hi = int(np.argmax(dev))
            if dev[o_hi] <= max_deviation:
                break
            # underfull candidates, emptiest first
            under = np.where(eligible, counts - target, np.inf)
            candidates = [int(o) for o in np.argsort(under) if under[o] < 0]
            moved = False
            for ps, row in enumerate(rows):
                if o_hi not in row or moved:
                    continue
                others = {domain.get(o) for o in row if o >= 0 and o != o_hi}
                for o_lo in candidates:
                    if o_lo in row or domain.get(o_lo) in others:
                        continue
                    if under[o_lo] >= dev[o_hi] - 1:
                        break  # no move can improve the spread
                    key = (pid, ps)
                    osdmap.pg_upmap_items.setdefault(key, []).append(
                        (o_hi, o_lo)
                    )
                    row[row.index(o_hi)] = o_lo
                    counts[o_hi] -= 1
                    counts[o_lo] += 1
                    changes.append((pid, ps, o_hi, o_lo))
                    moved = True
                    break
            if not moved:
                break
    if changes:  # one logical map revision per calc, as OSDMonitor commits
        osdmap.epoch += 1
    return changes
