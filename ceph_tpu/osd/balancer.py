"""Upmap balancer — the calc_pg_upmaps optimization loop on batched CRUSH.

Reference: src/osd/OSDMap.cc :: OSDMap::calc_pg_upmaps, driven by the mgr
balancer module (src/pybind/mgr/balancer/module.py, upmap mode): clone the
map, find over/underfull OSDs vs their weight-proportional PG share, and
emit pg_upmap_items entries moving PG shards from the fullest OSD to the
emptiest one that keeps the placement valid (same eligible device set,
distinct failure domains).  This is SURVEY.md §3.3's flagship batch-CRUSH
consumer: the full pool map runs as ONE crush_do_rule_batch launch on TPU,
and the greedy loop then only does sparse host-side bookkeeping — upmap
overrides never change the raw CRUSH output, so counts update incrementally
without re-descending.

The weight/target/count arithmetic lives in the shared scoring core
(osd/placement.py — cephplace), so the balancer, `ceph osd df`, the mgr
placement module, and osdmaptool all agree on what a deviation is.

The reference's loop additionally retries candidate deviations in a few
stochastic orders; this implementation is deterministic greedy (largest
deviation first), which the tests exploit for stable golden behavior.
"""
from __future__ import annotations

import numpy as np

from .osdmap import OSDMap
from .placement import (  # noqa: F401  (re-exported: historical import site)
    ideal_targets,
    pool_pg_counts,
    rule_osd_info,
    shard_counts,
)


def calc_pg_upmaps(
    osdmap: OSDMap,
    max_deviation: float = 1.0,
    max_iterations: int = 100,
    pools=None,
    mappings: dict | None = None,
) -> list[tuple[int, int, int, int]]:
    """Greedy upmap balance; mutates osdmap.pg_upmap_items.

    Returns the applied changes as (pool, ps, from_osd, to_osd) tuples —
    the analog of the incremental OSDMap::calc_pg_upmaps fills for the mgr
    balancer to commit.  max_deviation is in PG shards, as in the reference
    (osd_calc_pg_upmaps_max_deviation, default 1 → perfectly tight).
    `mappings` accepts precomputed {pool_id: (up, primaries)} map_pool
    results for the UNMUTATED map, so one batched sweep can feed both
    the caller's pre-pass score and this loop (the greedy bookkeeping is
    host-incremental — it never re-descends after its own changes, so a
    pre-change mapping is exactly what it starts from anyway)."""
    changes: list[tuple[int, int, int, int]] = []
    for pid in pools if pools is not None else sorted(osdmap.pools):
        pool = osdmap.pools[pid]
        weights, domain = rule_osd_info(osdmap, pool.crush_rule)
        if weights.sum() <= 0:
            continue
        if mappings is not None and pid in mappings:
            up = mappings[pid][0]
        else:
            up, _ = osdmap.map_pool(pid)
        rows = [list(r) for r in up]
        counts = shard_counts(up, osdmap.max_osd).astype(np.float64)
        shards = sum(1 for r in rows for o in r if o >= 0)
        target = ideal_targets(weights, shards)
        eligible = weights > 0

        for _ in range(max_iterations):
            dev = np.where(eligible, counts - target, -np.inf)
            o_hi = int(np.argmax(dev))
            if dev[o_hi] <= max_deviation:
                break
            # underfull candidates, emptiest first
            under = np.where(eligible, counts - target, np.inf)
            candidates = [int(o) for o in np.argsort(under) if under[o] < 0]
            moved = False
            for ps, row in enumerate(rows):
                if o_hi not in row or moved:
                    continue
                others = {domain.get(o) for o in row if o >= 0 and o != o_hi}
                for o_lo in candidates:
                    if o_lo in row or domain.get(o_lo) in others:
                        continue
                    if under[o_lo] >= dev[o_hi] - 1:
                        break  # no move can improve the spread
                    key = (pid, ps)
                    osdmap.pg_upmap_items.setdefault(key, []).append(
                        (o_hi, o_lo)
                    )
                    row[row.index(o_hi)] = o_lo
                    counts[o_hi] -= 1
                    counts[o_lo] += 1
                    changes.append((pid, ps, o_hi, o_lo))
                    moved = True
                    break
            if not moved:
                break
    if changes:  # one logical map revision per calc, as OSDMonitor commits
        osdmap.epoch += 1
    return changes
