"""Replica-side shard sub-op execution (reference: ECBackend::handle_sub_write/handle_sub_read).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations



import numpy as np

from ..common.crc32c import crc32c
from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.tracer import TRACER, TraceCtx
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MECSubOpReadReply,
    MECSubOpWrite,
    MECSubOpWriteReply,
    MPGClean,
    MPGNotify,
    MPGQuery,
    pack_data,
    unpack_data,
)
from .pg_log import LogEntry


class SubOpsMixin:
    # -- shard sub-ops -----------------------------------------------------
    def _load_fields(self) -> dict:
        """The `sender`/`qlen`/`degraded` kwargs every sub-op reply
        piggybacks (cephstorm): this OSD's id, its mClock queue depth,
        and the backend sentinel's degraded latch.  The primary's
        `_peer_load` map feeds cost-aware repair planning
        (`_plan_repair_read` skips loaded/degraded helpers)."""
        try:
            from ..common.kernel_telemetry import SENTINEL

            degraded = bool(SENTINEL.is_degraded)
        except Exception:
            degraded = False
        return {
            "sender": self.id,
            "qlen": self.scheduler.qlen(),
            "degraded": degraded,
        }

    def _handle_sub_write(self, conn, msg: MECSubOpWrite) -> None:
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        cid = self._cid(msg.pgid, msg.shard)
        retval = 0
        # cephtrace: the replica's commit joins the primary's subop span
        # across the daemon boundary (one attribute check when off)
        rspan = None
        if TRACER.enabled and getattr(msg, "trace_id", None) is not None:
            rspan = TRACER.begin(
                TraceCtx(msg.trace_id, msg.parent_span), "replica_commit",
                entity=self.whoami, shard=msg.shard, oid=msg.oid,
            )
        try:
            if (
                msg.epoch is not None
                and pg.interval_start
                and msg.epoch < pg.interval_start
            ):
                # sub-op from a PAST-interval primary (stale map racing
                # the change that re-elected this PG): refuse with the
                # DISTINCT -ESTALE code so the deposed sender knows to
                # step down rather than treat it as a flaky peer
                # (reference: ops tagged with an older
                # same_interval_since are dropped)
                TRACER.end(rspan, retval=-116)
                try:
                    conn.send_message(
                        MECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                           shard=msg.shard, retval=-116,
                                           **self._load_fields())
                    )
                except (OSError, ConnectionError):
                    pass
                return
            with pg.lock:
                entry_op = msg.entry[1] if msg.entry else None
                t = Transaction()
                t.try_create_collection(cid)
                if (
                    msg.data is not None
                    and getattr(msg, "mode", None) in ("range", "delta")
                ):
                    # partial-stripe RMW sub-op: splice (data shard) or
                    # GF-XOR (parity shard) into the stored chunk.  The
                    # per-object version guard (`over` -> `ver`) is what
                    # makes this safe: an RMW onto a STALE generation
                    # would fuse old and new stripes, and a REPLAYED RMW
                    # (dup/resend) would double-apply the delta.
                    stored_ver = self._stored_ver(cid, msg.oid)
                    if stored_ver == msg.version:
                        # already applied (idempotent replay): ack as-is
                        pass
                    elif (
                        getattr(msg, "over", None) is None
                        or stored_ver != msg.over
                        or msg.version != pg.version + 1
                    ):
                        raise IOError(
                            f"rmw v{msg.over}->v{msg.version} onto shard "
                            f"at obj v{stored_ver} pg v{pg.version}"
                        )
                    else:
                        seg = unpack_data(msg.data)
                        if crc32c(seg) != msg.crc:
                            raise IOError("rmw sub-op crc mismatch")
                        off = int(msg.off or 0)
                        try:
                            full = bytearray(self.store.read(cid, msg.oid))
                        except (NotFound, KeyError):
                            raise IOError("rmw target chunk missing on shard")
                        if off + len(seg) > len(full):
                            raise IOError("rmw beyond stored chunk")
                        # rot check BEFORE applying: stamping a fresh
                        # hinfo over a corrupt base would launder the rot
                        # past every later integrity check
                        try:
                            stored_h = int(
                                self.store.getattr(cid, msg.oid, "hinfo"))
                        except (NotFound, KeyError, ValueError):
                            stored_h = None
                        if (stored_h is not None
                                and crc32c(bytes(full)) != stored_h):
                            raise IOError("rmw base chunk failed hinfo")
                        if msg.mode == "delta":
                            seg = (
                                np.frombuffer(
                                    bytes(full[off:off + len(seg)]), np.uint8
                                )
                                ^ np.frombuffer(seg, np.uint8)
                            ).tobytes()
                        full[off:off + len(seg)] = seg
                        t.write(cid, msg.oid, off, seg)
                        t.setattr(cid, msg.oid, "hinfo",
                                  str(crc32c(bytes(full))).encode())
                        t.setattr(cid, msg.oid, "ver",
                                  str(msg.version).encode())
                        if msg.osize is not None:
                            t.setattr(cid, msg.oid, "size",
                                      str(msg.osize).encode())
                elif msg.data is not None:
                    chunk = unpack_data(msg.data)
                    if crc32c(chunk) != msg.crc:
                        raise IOError("chunk crc mismatch")
                    # generation-regression guard: a full-chunk push
                    # rebuilt from STALE sources (a donor that hasn't
                    # caught up across an acting permutation) must never
                    # overwrite a NEWER generation we hold — that is how
                    # an applied write gets rolled back cluster-wide.
                    # Equal/newer stamps apply (idempotent refresh /
                    # catch-up); wildcard pushes only land on chunks
                    # that carry no numeric stamp themselves.
                    stored_gen = self._stored_ver(cid, msg.oid)
                    push_gen = getattr(msg, "over", None)
                    if push_gen is None:
                        push_gen = msg.version
                    if stored_gen is not None and (
                        push_gen is None or push_gen < stored_gen
                    ):
                        raise IOError(
                            f"refusing generation regression "
                            f"v{push_gen} onto v{stored_gen}"
                        )
                    t.write(cid, msg.oid, 0, chunk)
                    t.truncate(cid, msg.oid, len(chunk))
                    t.setattr(cid, msg.oid, "hinfo", str(msg.crc).encode())
                    # full-chunk pushes stamp the chunk GENERATION: a
                    # recovery push carries the primary's stored stamp
                    # (`over`) since its bytes are rebuilt-current; a
                    # live write stamps its own version; a push that
                    # knows neither (backfill of a legacy object) stamps
                    # the wildcard so readers accept the bytes
                    gen = getattr(msg, "over", None)
                    if gen is None:
                        gen = msg.version
                    t.setattr(cid, msg.oid, "ver",
                              str(gen).encode() if gen else b"")
                    if msg.osize is not None:
                        t.setattr(cid, msg.oid, "size",
                                  str(msg.osize).encode())
                elif (
                    entry_op == "modify"
                    and msg.osize is not None
                    and msg.xattrs is None
                ):
                    # entry-only RMW companion (this shard's chunk bytes
                    # were untouched): keep the size xattr and object
                    # version current, but only if we actually hold the
                    # object — and only when our log is contiguous, else
                    # we'd stamp a version whose writes we missed.
                    # (`ver` is a CHUNK-GENERATION stamp: xattr-only
                    # pushes carry msg.xattrs and must not touch it —
                    # they don't change stripe bytes)
                    if msg.version is not None and msg.version == pg.version + 1:
                        try:
                            self.store.stat(cid, msg.oid)
                        except (NotFound, KeyError):
                            pass
                        else:
                            t.setattr(cid, msg.oid, "size",
                                      str(msg.osize).encode())
                            t.setattr(cid, msg.oid, "ver",
                                      str(msg.version).encode())
                elif entry_op in (None, "delete") and not msg.xattrs:
                    # data-less delete (live op or recovery replay)
                    try:
                        self.store.stat(cid, msg.oid)
                        t.remove(cid, msg.oid)
                    except (NotFound, KeyError):
                        pass
                # else: entry-only push ("modify" log replay / "clean"
                # seal / xattr-only update) — no data op
                if msg.xattrs is not None:
                    if msg.data is not None:
                        # riding a data push (recovery): the dict is a FULL
                        # snapshot — stale attrs a removal we missed must
                        # not survive
                        self._apply_xattr_updates(
                            t, cid, msg.oid, msg.xattrs, snapshot=True
                        )
                    else:
                        # live xattr-only update: apply ONLY if this shard
                        # holds the object; a shard that missed the write
                        # must not grow a phantom zero-length object
                        # (recovery pushes data + attrs together later)
                        try:
                            self.store.stat(cid, msg.oid)
                        except (NotFound, KeyError):
                            pass
                        else:
                            self._apply_xattr_updates(
                                t, cid, msg.oid, msg.xattrs
                            )
                if getattr(msg, "rmattrs", None):
                    # atomic-with-data attr removals (cache-tier clean
                    # clear riding a mutation); only if we hold the object
                    try:
                        existing = set(self.store.getattrs(cid, msg.oid))
                    except (NotFound, KeyError):
                        existing = set()
                    for name in msg.rmattrs:
                        if f"u_{name}" in existing:
                            t.rmattr(cid, msg.oid, f"u_{name}")
                if getattr(msg, "omap", None) is not None:
                    # live omap mutation or recovery snapshot: omap
                    # exists on replicated pools only; an omap op on a
                    # fresh oid creates the object (touch), matching the
                    # primary's transaction
                    t.touch(cid, msg.oid)
                    self._apply_omap(t, cid, msg.oid, msg.omap)
                    if (msg.data is None and msg.version is not None
                            and msg.version == pg.version + 1):
                        # live omap-only update on a log-contiguous
                        # shard: stamp the version for dup verification
                        t.setattr(cid, msg.oid, "ver",
                                  str(msg.version).encode())
                if (
                    msg.entry is not None
                    and msg.version is not None
                    and msg.version > pg.version
                ):
                    if entry_op == "clean":
                        # a clean that JUMPS our version means we were
                        # backfilled across a gap: seal an empty log window
                        # so covers() stays honest about what we can vouch
                        # for entry-by-entry
                        self._log_seal_txn(t, cid, pg, msg.version)
                    elif msg.version == pg.version + 1:
                        entry = LogEntry.from_list(msg.entry)
                        self._log_txn(t, cid, pg, entry)
                    # else: the entry JUMPS our version (we missed writes —
                    # e.g. a sub-write lost while the primary acked at
                    # min_size).  Apply the data but refuse the log append:
                    # advancing head across a hole would make this shard
                    # report itself clean at a version whose intermediate
                    # objects it does not hold.  Our stale version makes
                    # the primary's next recovery tick replay the gap.
                self.store.queue_transaction(t)
                # cephread belt-and-braces: a replica apply supersedes
                # any object this daemon cached while IT was primary (a
                # flapped-back primary's stale entry would otherwise
                # survive until version validation catches it)
                self._read_cache_invalidate(msg.pgid, msg.oid)
        except Exception as e:
            self.cct.dout("osd", 0, f"{self.whoami} sub_write failed: {e!r}")
            retval = -5
        else:
            self.logger.inc("subop_w")
        TRACER.end(rspan, retval=retval)
        try:
            conn.send_message(
                MECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                   shard=msg.shard, retval=retval,
                                   **self._load_fields())
            )
        except (OSError, ConnectionError):
            pass

    def _handle_sub_read(self, conn, msg: MECSubOpRead) -> None:
        cid = self._cid(msg.pgid, msg.shard)
        if getattr(msg, "reads", None):
            self._handle_sub_read_multi(conn, msg, cid)
            return
        try:
            # "osd.ec.shard_read" (legacy: osd_debug_inject_read_err) —
            # an error action makes this shard answer EIO, forcing the
            # primary onto the reconstruct-from-survivors path
            failpoint("osd.ec.shard_read", cct=self.cct,
                      entity=self.whoami, pgid=msg.pgid, shard=msg.shard,
                      oid=msg.oid)
        except FailpointCrash:
            raise
        except FailpointError:
            try:
                conn.send_message(MECSubOpReadReply(
                    tid=msg.tid, pgid=msg.pgid, oid=msg.oid,
                    shard=msg.shard, retval=-5, data=None, size=None,
                    xattrs=None, ver=None, **self._load_fields(),
                ))
            except (OSError, ConnectionError):
                pass
            return
        try:
            if msg.offsets == []:
                # metadata-only probe: existence + size/xattrs, no body
                self.store.stat(cid, msg.oid)
                data = b""
            elif msg.offsets:
                # ranged reads feed RMW old-byte fetches and CLAY repair:
                # verify the WHOLE chunk's hinfo first — serving rotted
                # bytes here would poison a parity delta with a fresh CRC
                # stamped over it (no rot check could catch it later)
                whole = self.store.read(cid, msg.oid)
                try:
                    stored = int(self.store.getattr(cid, msg.oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(whole) != stored:
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on ranged read "
                        f"{msg.pgid}/{msg.oid} shard {msg.shard}",
                    )
                    raise NotFound(msg.oid)
                parts = []
                for off, ln in msg.offsets:
                    if ln == -1:
                        parts.append(whole)
                    else:
                        parts.append(whole[off:off + ln])
                data = b"".join(parts)
            else:
                data = self.store.read(cid, msg.oid)
                # full-chunk read: verify at-rest integrity against the
                # stored hinfo CRC before serving — a rotted chunk must
                # read as MISSING so the primary reconstructs instead of
                # decoding garbage (reference: ECBackend checks
                # ECUtil::HashInfo on read, -EIO on mismatch)
                try:
                    stored = int(self.store.getattr(cid, msg.oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(data) != stored:
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on read "
                        f"{msg.pgid}/{msg.oid} shard {msg.shard}",
                    )
                    raise NotFound(msg.oid)
            try:
                size = int(self.store.getattr(cid, msg.oid, "size"))
            except (NotFound, KeyError):
                size = None
            try:
                user = {
                    n[2:]: pack_data(v)
                    for n, v in self.store.getattrs(cid, msg.oid).items()
                    if n.startswith("u_")
                }
            except (NotFound, KeyError):
                user = None
            reply = MECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, shard=msg.shard,
                retval=0, data=pack_data(data), size=size, xattrs=user,
                ver=self._stored_ver(cid, msg.oid),
                **self._load_fields(),
            )
        except (NotFound, KeyError):
            reply = MECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, oid=msg.oid, shard=msg.shard,
                retval=-2, data=None, size=None, xattrs=None, ver=None,
                **self._load_fields(),
            )
        try:
            conn.send_message(reply)
        except (OSError, ConnectionError):
            pass

    def _handle_sub_read_multi(self, conn, msg: MECSubOpRead, cid) -> None:
        """cephread batched branch: serve a `reads=[[oid, off, ln], ...]`
        list in one reply (the read batcher's one fan-out per flush).
        Per-entry semantics match the single-oid path exactly — the
        `osd.ec.shard_read` failpoint fires once per entry (so a
        thrasher `times(n,error)` spec EIOs n entries, not n batches),
        the WHOLE chunk's hinfo CRC is verified before any slice is
        served, and a missing/rotted entry answers its own -2/-5 row
        without failing siblings."""
        rows = []
        for ent in msg.reads:
            oid, off, ln = ent[0], ent[1], ent[2]
            try:
                failpoint("osd.ec.shard_read", cct=self.cct,
                          entity=self.whoami, pgid=msg.pgid,
                          shard=msg.shard, oid=oid)
            except FailpointCrash:
                raise
            except FailpointError:
                rows.append([-5, None, None, None])
                continue
            try:
                whole = self.store.read(cid, oid)
                try:
                    stored = int(self.store.getattr(cid, oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(whole) != stored:
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on batched read "
                        f"{msg.pgid}/{oid} shard {msg.shard}",
                    )
                    raise NotFound(oid)
                data = whole if off is None else whole[off:off + ln]
                try:
                    size = int(self.store.getattr(cid, oid, "size"))
                except (NotFound, KeyError):
                    size = None
                rows.append([0, pack_data(data), size,
                             self._stored_ver(cid, oid)])
            except (NotFound, KeyError):
                rows.append([-2, None, None, None])
        try:
            conn.send_message(MECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, oid=None, shard=msg.shard,
                retval=0, data=None, size=None, xattrs=None, ver=None,
                results=rows, **self._load_fields(),
            ))
        except (OSError, ConnectionError):
            pass

    def _handle_pg_query(self, conn, msg: MPGQuery) -> None:
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        cid = self._cid(msg.pgid, msg.shard)
        oids = []
        try:
            oids = sorted(
                o for o in self.store.list_objects(cid)
                if not o.startswith("_")
            )
        except (NotFound, KeyError):
            pass
        try:
            conn.send_message(
                MPGNotify(tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
                          version=pg.version, log_start=pg.log.tail,
                          oids=oids, last_epoch=pg.last_map_epoch)
            )
        except (OSError, ConnectionError):
            pass

    def _handle_pg_clean(self, msg: MPGClean) -> None:
        """Primary says the PG went clean at `epoch` (the
        last_epoch_clean role): advance the persisted rebuild floor and
        drop local interval history — settled intervals must never
        re-block a future peering round.  A clean claim from a PAST
        interval is ignored (a deposed primary cannot retro-settle
        history it no longer owns)."""
        pool_id, ps = msg.pgid.split(".")
        pg = self._pg(int(pool_id), int(ps))
        with pg.lock:
            if msg.epoch < pg.interval_start:
                return
            pg.last_map_epoch = max(pg.last_map_epoch, int(msg.epoch))
            pg.past_intervals.clear()
            pg.intervals_rebuilt = False
            self._save_intervals(pg)

