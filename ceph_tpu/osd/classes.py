"""Object classes — server-side op extensions (reference: src/objclass +
src/cls; `rados exec` in librados; SURVEY.md §2.6).

A class method runs AT THE PRIMARY, under the PG lock, against one
object: it reads the object's current state through a `ClsHandle` and
stages mutations that commit as ONE replicated, logged transaction after
the method returns.  That primary-side atomicity is the entire point —
e.g. the bucket-index update cls_rgw performs server-side cannot be
raced by a second gateway the way client-side read-modify-write can.

Contract (mirrors objclass.h, minus the C plumbing):

    def method(hctx: ClsHandle, inp: dict) -> tuple[int, object]:
        ...
    ClassRegistry.instance().register("mycls", "mymethod", method)

- `inp` and the returned payload must be JSON-serializable (they ride
  the MOSDOp/MOSDOpReply wire).
- retval < 0 aborts: staged mutations are DISCARDED and the retval goes
  back to the client (e.g. -17 EEXIST for a failed create guard).
- Methods must be deterministic state transforms of (object, inp) —
  they may be re-run on a client resend that lost its reply (the dup
  cache answers applied resends, but a method that consults wall-clock
  or randomness would still diverge across primaries).

Built-ins registered at import:

- `rgw` (reference: src/cls/rgw — the bucket-index class):
  `dir_entry_create`  {key, val}            -17 if key exists
  `dir_entry_remove`  {key}                 -2 if absent
  `index_update`      {add: {k: v}, rm: [k], guard_absent: [k]}
                      atomic multi-key set+remove; -17 if any guard key
                      is present; -2 if the index is sealed
  `bucket_seal`       {}                    atomic check-empty +
                      tombstone; -39 ENOTEMPTY if entries remain
  `bucket_init`       {}                    reset a (re)created bucket's
                      index: clears seals and ghost entries
- `counter` (test/demo of primary-side atomicity, the hello.cc role):
  `incr`              {key, delta}          returns the new value
"""
from __future__ import annotations

import json


class ClsHandle:
    """Per-invocation object view + mutation stager (reference:
    cls_method_context_t).  Reads see the object's committed state;
    writes stage into `omap_set`/`omap_rm`/`data` for the caller
    (_exec_op) to commit atomically."""

    def __init__(self, oid: str, read_data, read_omap):
        self.oid = oid
        self._read_data = read_data
        self._read_omap = read_omap
        self.staged_set: dict[str, bytes] = {}
        self.staged_rm: set[str] = set()
        self.staged_data: bytes | None = None

    # -- reads -------------------------------------------------------------
    def read(self) -> bytes | None:
        """Object data; None when the object does not exist."""
        if self.staged_data is not None:
            return self.staged_data
        return self._read_data()

    def omap_get(self, keys=None) -> dict[str, bytes]:
        """Committed omap overlaid with this invocation's staged state
        (a method observes its own writes, like a cls transaction)."""
        kv = dict(self._read_omap())
        for k in self.staged_rm:
            kv.pop(k, None)
        kv.update(self.staged_set)
        if keys is not None:
            return {k: kv[k] for k in keys if k in kv}
        return kv

    # -- staged writes -----------------------------------------------------
    def write_full(self, data: bytes) -> None:
        self.staged_data = bytes(data)

    def omap_set(self, kv: dict[str, bytes]) -> None:
        for k, v in kv.items():
            self.staged_rm.discard(k)
            self.staged_set[k] = bytes(v)

    def omap_rm(self, keys) -> None:
        for k in keys:
            self.staged_set.pop(k, None)
            self.staged_rm.add(k)

    @property
    def dirty(self) -> bool:
        return bool(self.staged_set or self.staged_rm
                    or self.staged_data is not None)


class ClassRegistry:
    """Process-global method table (reference: ClassHandler; classes load
    once per OSD process)."""

    _instance: "ClassRegistry | None" = None

    def __init__(self):
        self._methods: dict[tuple[str, str], object] = {}

    @classmethod
    def instance(cls) -> "ClassRegistry":
        if cls._instance is None:
            cls._instance = ClassRegistry()
            _register_builtins(cls._instance)
        return cls._instance

    def register(self, cls_name: str, method: str, fn) -> None:
        self._methods[(cls_name, method)] = fn

    def get(self, cls_name: str, method: str):
        """None when unknown — the OSD answers -EOPNOTSUPP, like the
        reference's class-load failure."""
        return self._methods.get((cls_name, method))


# ---------------------------------------------------------------- built-ins

def _rgw_dir_entry_create(hctx: ClsHandle, inp: dict):
    """Create-if-absent — the atomic 'claim' two concurrent gateways race
    for (reference: cls_rgw bucket creation guards)."""
    key = inp["key"]
    if key in hctx.omap_get(keys=[key]):
        return -17, f"entry {key!r} exists"
    hctx.omap_set({key: json.dumps(inp.get("val")).encode()})
    return 0, None


def _rgw_dir_entry_remove(hctx: ClsHandle, inp: dict):
    key = inp["key"]
    if key not in hctx.omap_get(keys=[key]):
        return -2, f"no entry {key!r}"
    hctx.omap_rm([key])
    return 0, None


# reserved omap key marking a sealed (deleted) bucket index; sorts below
# every printable object key so listings naturally skip it
SEALED_KEY = "\x01sealed"


def _rgw_index_update(hctx: ClsHandle, inp: dict):
    """Transactional multi-key index mutation (reference: cls_rgw
    bucket-index complete ops): adds + removes land atomically, optional
    guards refuse the whole batch if a key already exists, and adds are
    refused outright on a SEALED index (a concurrently deleted bucket) —
    the check and the mutation share one PG-lock critical section, so a
    PUT can never land an entry in a bucket another gateway deleted."""
    add = inp.get("add") or {}
    if add and SEALED_KEY in hctx.omap_get(keys=[SEALED_KEY]):
        return -2, "bucket index sealed (bucket deleted)"
    for key in inp.get("guard_absent") or []:
        if key in hctx.omap_get(keys=[key]):
            return -17, f"guard: entry {key!r} exists"
    hctx.omap_set({k: json.dumps(v).encode() for k, v in add.items()})
    rm = inp.get("rm") or []
    hctx.omap_rm(rm)
    return 0, {"added": len(add), "removed": len(rm)}


def _rgw_bucket_seal(hctx: ClsHandle, inp: dict):
    """Atomic check-empty-and-tombstone (reference: cls_rgw's bucket
    removal guards): refuses with -39 ENOTEMPTY if any live entry
    remains, else marks the index sealed so racing adds fail.  The whole
    op runs under the PG lock, closing the check-then-delete window a
    client-side emptiness test leaves open."""
    live = [k for k in hctx.omap_get() if not k.startswith("\x01")]
    if live:
        return -39, {"entries": len(live)}
    hctx.omap_set({SEALED_KEY: b"1"})
    return 0, None


def _rgw_bucket_init(hctx: ClsHandle, inp: dict):
    """Reset an index object for a (re)created bucket: drops a stale
    seal and any ghost entries a half-completed delete left behind."""
    hctx.omap_rm(list(hctx.omap_get()))
    hctx.write_full(b"")
    return 0, None


def _counter_incr(hctx: ClsHandle, inp: dict):
    """Atomic read-modify-write under the PG lock — the op that LOSES
    updates when done client-side by two concurrent writers."""
    key = inp.get("key", "value")
    cur = hctx.omap_get(keys=[key]).get(key)
    val = (int(cur) if cur else 0) + int(inp.get("delta", 1))
    hctx.omap_set({key: str(val).encode()})
    return 0, {"value": val}


def _register_builtins(reg: ClassRegistry) -> None:
    reg.register("rgw", "dir_entry_create", _rgw_dir_entry_create)
    reg.register("rgw", "dir_entry_remove", _rgw_dir_entry_remove)
    reg.register("rgw", "index_update", _rgw_index_update)
    reg.register("rgw", "bucket_seal", _rgw_bucket_seal)
    reg.register("rgw", "bucket_init", _rgw_bucket_init)
    reg.register("counter", "incr", _counter_incr)
