"""OSD data-plane wire messages (reference: src/messages/MOSDOp.h,
MOSDOpReply.h, MOSDECSubOpWrite.h/MOSDECSubOpRead.h via src/osd/ECMsgTypes.h,
and the pg_query/pg_log peering messages; SURVEY.md §3.1-3.2).

Bulk payloads (object data, chunk bytes) ride as latin-1-safe base64 inside
the JSON body — the framing/crc below is byte-exact either way, and these
messages are small control frames plus one data segment, matching the
reference's header/front/data split in spirit if not in zero-copy.
"""
from __future__ import annotations

import base64

from ..mon.messages import _JsonMessage
from ..msg.message import register_message


def pack_data(data: bytes | None) -> str | None:
    return None if data is None else base64.b64encode(bytes(data)).decode()


def unpack_data(s: str | None) -> bytes | None:
    return None if s is None else base64.b64decode(s)


@register_message
class MOSDOp(_JsonMessage):
    """Client object op to the PG primary (reference: MOSDOp).

    op: write_full | read | delete | stat | list (pg listing for tools).
    `epoch` is the client's map epoch: a primary on a newer map NACKs with
    -ESTALE so the client refreshes and resends (Objecter resend rule).
    `ps` overrides the oid-hash placement seed — the PG-split migrator
    addresses an object still living in its pre-split PG this way (the
    reference reaches old PGs through pg history / past_intervals).
    `snapid` on reads selects the pool-snapshot view of the object
    (served from the newest clone at-or-after that id, else the head).
    `snap_seq` on writes is the client's snap context: the primary clones
    against max(its map's seq, the client's) so a write never races the
    map push after a mksnap (reference: the SnapContext in every MOSDOp).
    `reqid` is the client-unique id of the LOGICAL op, stable across
    resends (reference: osd_reqid_t): the primary's per-PG dup cache
    answers a resent already-applied mutation from it instead of
    re-executing (reference: pg_log dup detection), which is what makes
    append and partial-stripe RMW retry-safe.
    `trace_id`/`parent_span` carry the cephtrace context minted at
    Objecter.op_submit (head-based sampling; None = unsampled).  The
    names deliberately avoid the framing attrs send_message stamps
    (`seq`/`src` — the CL6 field-shadow trap) so the payload values
    survive the wire; tests/test_analyzer_proto.py audits this.
    """

    MSG_TYPE = 42
    FIELDS = ("tid", "pool", "oid", "op", "data", "epoch", "off", "length",
              "ps", "snapid", "snap_seq", "reqid", "trace_id", "parent_span")


@register_message
class MOSDOpReply(_JsonMessage):
    """reference: MOSDOpReply — retval + (for reads) data + map epoch."""

    MSG_TYPE = 43
    FIELDS = ("tid", "retval", "data", "epoch", "result")


@register_message
class MECSubOpWrite(_JsonMessage):
    """Primary → shard OSD: store one chunk (reference: MOSDECSubOpWrite
    carrying ECSubWrite: tid, shard transactions, log entries).

    `entry` is the pg_log entry [version, op, oid(, reqid)] the shard
    must append atomically with the chunk write (delta-recovery
    bookkeeping; the optional reqid makes dup detection survive primary
    changes).  `osize` carries the OBJECT size of a modify so every
    shard can answer stat/padding-strip.
    `xattrs` carries user-xattr updates {name: b64 | null-to-remove},
    applied in the same transaction (librados xattr replication).

    `mode`/`off` carry the partial-stripe RMW sub-ops (reference:
    src/osd/ECTransaction.cc :: generate_transactions — here expressed
    as parity-delta writes, the optimized-EC formulation):
      mode=None  — full-chunk replace (the classic write_full path)
      mode="range" — splice `data` into the chunk at byte `off`
      mode="delta" — GF(2^8)-XOR `data` onto the chunk at byte `off`
                     (parity shards of an RMW)
    Both RMW modes recompute the chunk's hinfo CRC after applying.
    `over` is the object version the RMW transitions FROM: a shard whose
    stored per-object `ver` xattr differs refuses (it is stale and will
    be rebuilt by recovery), and one already at the target version acks
    as a no-op (idempotent replay) — the object_info_t version guard.

    `omap` carries omap mutations or a recovery snapshot:
      {"set": {key: b64}, "rm": [key...], "clear": bool} applied in the
      same transaction; {"snapshot": {key: b64}} replaces the whole omap
      (recovery push, mirroring the xattr snapshot semantics).

    `rmattrs` lists user-xattr names removed in the same transaction as
    a data write (cache-tier dirty marking: the tier.clean clear must be
    atomic with the mutation it rides — see daemon._cache_tier_op's
    state model; `xattrs` can't carry it on a data push because a
    data+xattrs message means a full recovery snapshot).

    `trace_id`/`parent_span` propagate the primary's cephtrace context
    (parent = the primary's `subop` fan-out span) so the replica's
    commit span joins the client's trace tree across daemons."""

    MSG_TYPE = 108
    FIELDS = ("tid", "pgid", "oid", "shard", "data", "crc", "version",
              "entry", "epoch", "xattrs", "mode", "off", "over", "osize",
              "omap", "rmattrs", "trace_id", "parent_span")


@register_message
class MECSubOpWriteReply(_JsonMessage):
    """`sender`/`qlen`/`degraded` (cephstorm) piggyback the replying
    OSD's load on every ack: its id, its mClock queue depth, and its
    backend-sentinel degraded latch.  The primary's repair planner
    reads them from `_peer_load` to skip expensive helpers
    (`_plan_repair_read`); None = an old peer, cost-unaware planning.
    The names avoid the framing attrs (`seq`/`src` — CL6)."""

    MSG_TYPE = 109
    FIELDS = ("tid", "pgid", "shard", "retval", "sender", "qlen",
              "degraded")


@register_message
class MECSubOpRead(_JsonMessage):
    """Primary → shard OSD: fetch chunk bytes (reference: MOSDECSubOpRead).
    `offsets` carries optional (off, len) sub-chunk ranges (CLAY repair).
    `trace_id`/`parent_span` propagate the cephtrace context for traced
    reads (RMW old-byte fetches, degraded-read gathers).

    `reads` (cephread) generalizes the PR-13 multi-range machinery to
    multiple objects: a list of `[oid, off, ln]` entries (off/ln None =
    whole chunk) served in one round trip — the read batcher's one
    sub-op fan-out per flush.  When `reads` is set, `oid`/`offsets` are
    unused and the reply carries per-entry `results` rows instead."""

    MSG_TYPE = 110
    FIELDS = ("tid", "pgid", "oid", "shard", "offsets", "epoch",
              "trace_id", "parent_span", "reads")


@register_message
class MECSubOpReadReply(_JsonMessage):
    """`size` echoes the shard's stored object-size xattr so a primary
    without its own shard copy can still strip stripe padding; `xattrs`
    echoes the user xattrs for the same degraded-primary case.  `ver`
    echoes the stored per-object version xattr (None = unversioned /
    backfilled-wildcard) so readers can reject stale-generation chunks.

    `results` answers a multi-oid `reads` request: one
    `[retval, data(base64), size, ver]` row per request entry, aligned
    by index (`oid`/`data`/`size`/`ver` are None on a batched reply —
    the rows carry everything).

    `sender`/`qlen`/`degraded` (cephstorm) piggyback the replying OSD's
    load — see MECSubOpWriteReply."""

    MSG_TYPE = 111
    FIELDS = ("tid", "pgid", "oid", "shard", "retval", "data", "size",
              "xattrs", "ver", "results", "sender", "qlen", "degraded")


@register_message
class MPGQuery(_JsonMessage):
    """Primary → peer shard: 'what is your PG state?' (reference: MOSDPGQuery
    driving PeeringState; here the peering-lite version: version + log
    bounds so the primary can pick delta vs backfill)."""

    MSG_TYPE = 112
    FIELDS = ("tid", "pgid", "shard", "epoch")


@register_message
class MPGNotify(_JsonMessage):
    """Peer shard → primary: PG info reply (reference: MOSDPGNotify).
    version: last applied version; log_start: oldest version still in the
    bounded log (0 = log covers from the beginning); last_epoch: newest
    map epoch the peer logged a write under (reference: pg_history_t
    riding pg_info_t in notifies) — a freshly-assigned primary with no
    local history uses the minimum over peers as the starting point to
    rebuild PastIntervals from the mon's map archive."""

    MSG_TYPE = 113
    FIELDS = ("tid", "pgid", "shard", "version", "log_start", "oids",
              "last_epoch")


@register_message
class MPGPull(_JsonMessage):
    """Stale primary → ahead peer: 'push me your log delta' (reference:
    peering's authoritative-log adoption — the revived primary catches
    ITSELF up before judging peers; without this it would mint duplicate
    versions and judge ahead-peers clean).  `have_oids` is the
    requester's local object list so the donor can push deletes for
    objects that no longer exist (a survivors-only backfill would
    resurrect deletions).

    `trace_id`/`parent_span` carry the requester's cephheal recovery
    trace context (parent = its `recovery_pull` span, opened BEFORE the
    send) so the donor's rebuild/push spans join the recovery tree
    across daemons.  Named to dodge the framing attrs send_message
    stamps (`seq`/`src` — the CL6 field-shadow trap), like the PR-9
    client-op fields."""

    MSG_TYPE = 116
    FIELDS = ("tid", "pgid", "shard", "from_version", "epoch", "have_oids",
              "trace_id", "parent_span")


@register_message
class MPGPullReply(_JsonMessage):
    """`trace_id`/`parent_span` echo the request's context (the donor's
    completion joining the same recovery tree) — same field-shadow-safe
    naming as MPGPull."""

    MSG_TYPE = 117
    FIELDS = ("tid", "pgid", "shard", "retval", "trace_id", "parent_span")


@register_message
class MOSDPingMsg(_JsonMessage):
    """OSD↔OSD heartbeat (reference: MOSDPing PING/PING_REPLY)."""

    MSG_TYPE = 70
    FIELDS = ("op", "osd", "epoch")


@register_message
class MScrubShard(_JsonMessage):
    """Primary → shard OSD: report your digests for a PG shard
    (reference: MOSDRepScrub requesting a ScrubMap)."""

    MSG_TYPE = 114
    FIELDS = ("tid", "pgid", "shard", "epoch")


@register_message
class MScrubShardReply(_JsonMessage):
    """Shard ScrubMap: oid -> [computed_crc, stored_crc_or_null, size]
    (reference: ScrubMap::object digests; stored != computed means the
    shard's at-rest data rotted under its own hinfo)."""

    MSG_TYPE = 115
    FIELDS = ("tid", "pgid", "shard", "objects")


@register_message
class MWatchNotify(_JsonMessage):
    """Primary OSD → watcher client: a notify fired on a watched object
    (reference: MWatchNotify carrying notify_id/cookie/payload).  The
    watcher replies with MWatchNotifyAck so the notifier's collect
    phase can complete (reference: notify_ack op)."""

    MSG_TYPE = 118
    FIELDS = ("notify_id", "pool", "oid", "cookie", "data")


@register_message
class MWatchNotifyAck(_JsonMessage):
    MSG_TYPE = 119
    FIELDS = ("notify_id", "pool", "oid", "cookie")


@register_message
class MPGClean(_JsonMessage):
    """Primary → acting replicas: the PG went CLEAN in the current
    interval at `epoch` (reference: last_epoch_clean riding pg_info /
    MOSDPGInfo).  Replicas bump their persisted interval-rebuild floor
    and drop their own past-interval history — intervals older than a
    clean point are settled and must never re-block a future peering
    round (their members may be long gone while every byte lives on in
    the clean acting set)."""

    MSG_TYPE = 121
    FIELDS = ("pgid", "shard", "epoch")
