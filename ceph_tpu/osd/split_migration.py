"""PG split migration on pg_num increase (reference: PG::split_into + the upmap-era split machinery).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations




from ..store.object_store import NotFound
from .messages import (
    MOSDOp,
)
from ..osd.osdmap import PG_POOL_ERASURE, object_ps
from .messages import MOSDPingMsg
from .pg import CLONE_SEP


class SplitMigrationMixin:
    # -- PG split migration (pg_num increase) ------------------------------
    def _split_pass_work(self) -> None:
        try:
            self._split_pass()
            self._snaptrim_pass()
            self._tier_agent_pass()
        finally:
            with self._lock:
                self._split_inflight = False

    def _split_pass(self) -> None:
        """Migrate objects stranded in pre-split PGs (reference: PG split —
        OSD::split_pgs + backfill; here the old-PG primary rewrites each
        misplaced object through the normal client-op path to its
        post-split PG, then deletes the old copy).

        Eventually consistent: the pass re-runs every tick until each
        primary PG has been scanned clean under the current pg_num, so an
        OSD that was down during the split finishes the job when it
        returns.  Window semantics: until an object is migrated, clients
        on the new map read -ENOENT from the post-split PG (the reference
        covers this window with pg history + peering; SURVEY's data plane
        accepts the brief window)."""
        m = self.osdmap
        if m is None:
            return
        for pgid, pg in list(self.pgs.items()):
            if self._stop.is_set():
                return
            pool = m.pools.get(pg.pool_id)
            if pool is None or pg.split_scanned >= pool.pg_num:
                continue
            _acting, primary = self._acting(pg.pool_id, pg.ps)
            if primary != self.id:
                continue  # re-checked next pass (primary may change)
            try:
                self._split_migrate_pg(pg, pool)
                pg.split_scanned = pool.pg_num
            except Exception as e:
                self.cct.dout(
                    "osd", 1, f"{self.whoami} split pass {pgid}: {e!r}"
                )

    def _split_migrate_pg(self, pg, pool) -> None:
        # raw store listing: snapshot clones are hidden from the client
        # `list` op but must migrate with their head
        acting, _p = self._acting(pg.pool_id, pg.ps)
        if self.id not in acting:
            return
        try:
            names = self.store.list_objects(
                self._primary_cid(pg, pool, acting)
            )
        except (NotFound, KeyError):
            return
        for oid in sorted(names):
            if oid.startswith("_"):
                continue
            head = oid.split(CLONE_SEP, 1)[0]
            new_ps = object_ps(head, pool.pg_num)
            if new_ps != pg.ps:
                self._migrate_object(pg, pool, oid, new_ps)

    def _forward_op(self, target: int, msg: MOSDOp):
        """Execute an op locally when this OSD is the target primary, else
        ship it and wait (the OSD acting as its own Objecter)."""
        if target == self.id:
            return self._execute_client_op(msg)
        conn = self._conn_to_osd(target)
        conn.send_message(msg)
        return self._wait_reply(msg.tid, timeout=15.0)

    def _migrate_object(self, pg, pool, oid: str, new_ps: int) -> None:
        """write-to-new-PG before delete-from-old: a crash mid-migration
        leaves a duplicate (invisible: lookups hash to the new PG), never
        a loss.

        Lost-update guard: a client on the new map may have ALREADY
        written the object into its post-split PG; the stale pre-split
        copy must not clobber it, so the destination is stat'd first and
        a hit just drops the old copy.  (A write landing between the stat
        and our write is the residual window; the reference closes it
        with peering's authoritative log — out of scope here and noted.)
        """
        e = self.my_epoch()
        _a, new_primary = self._acting(pg.pool_id, new_ps)
        # every dest op carries the explicit post-split ps: snapshot-clone
        # names would hash elsewhere (placement follows their HEAD object)
        st = self._forward_op(new_primary, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="stat",
            epoch=e, ps=new_ps,
        ))
        if st is not None and st.retval == 0:
            # newer post-split copy exists: just retire the stale one
            d = self._execute_client_op(MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="delete", epoch=e, ps=pg.ps,
            ))
            if d.retval != 0:
                raise RuntimeError(f"split retire {oid}: {d.result}")
            return
        r = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="read",
            epoch=e, ps=pg.ps, off=0, length=0,
        ))
        if r.retval != 0:
            raise RuntimeError(f"split read {oid}: {r.result}")
        xr = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid,
            op="getxattrs", epoch=e, ps=pg.ps,
        ))
        xattrs = xr.result if xr.retval == 0 else None
        w = self._forward_op(new_primary, MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid,
            op="write_full", data=r.data, epoch=e, ps=new_ps,
        ))
        if w is None or w.retval != 0:
            raise RuntimeError(
                f"split write {oid}: {w.result if w else 'timeout'}"
            )
        if xattrs:
            xw = self._forward_op(new_primary, MOSDOp(
                tid=self._next_tid(), pool=pg.pool_id, oid=oid,
                op="setxattr", data=xattrs, epoch=e, ps=new_ps,
            ))
            if xw is None or xw.retval != 0:
                raise RuntimeError(
                    f"split xattrs {oid}: {xw.result if xw else 'timeout'}"
                )
        d = self._execute_client_op(MOSDOp(
            tid=self._next_tid(), pool=pg.pool_id, oid=oid, op="delete",
            epoch=e, ps=pg.ps,
        ))
        if d.retval != 0:
            raise RuntimeError(f"split delete {oid}: {d.result}")
        self.cct.dout(
            "osd", 10,
            f"{self.whoami} split: migrated {oid} "
            f"{pg.pool_id}.{pg.ps} -> {pg.pool_id}.{new_ps}",
        )

    def _maybe_schedule_scrub(self, now: float) -> None:
        """Periodic deep scrub of primary PGs (reference: OSD::sched_scrub;
        osd_deep_scrub_interval 0 disables — tests drive scrub_pg
        directly)."""
        interval = self.cct.conf.get("osd_deep_scrub_interval")
        if not interval or now - self._last_scrub < interval:
            return
        self._last_scrub = now
        m = self.osdmap
        if m is None:
            return
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                try:
                    _acting, primary = self._acting(pool_id, ps)
                except KeyError:
                    continue
                if primary != self.id:
                    continue
                pgid = f"{pool_id}.{ps}"
                if pgid in self._scrubs_queued:
                    continue  # scrubs outlasting the interval must not pile
                self._scrubs_queued.add(pgid)

                def scrub_work(pid=pool_id, s=ps, key=pgid):
                    try:
                        self.scrub_pg(pid, s)
                    finally:
                        self._scrubs_queued.discard(key)

                self.scheduler.enqueue("background_scrub", scrub_work)

    def _mgr_report(self) -> None:
        """Stream a perf snapshot to the mgr (reference: MgrClient sending
        MMgrReport on its tick)."""
        addr = self.cct.conf.get("mgr_addr")
        if not addr:
            return
        from ..common.kernel_telemetry import backend_health
        from ..mgr.messages import MMgrReport

        host, _, port = addr.rpartition(":")
        with self._pgs_lock:
            num_pgs = len(self.pgs)
        # the store scan runs UNLOCKED: heartbeats/recovery/map-apply all
        # contend on _pgs_lock, and an O(objects) walk per report tick
        # must not delay them toward the failure-report threshold
        num_objects = 0
        pool_bytes: dict[int, int] = {}
        pool_objects: dict[int, int] = {}
        coll_objects: dict[str, int] = {}  # cid -> objects (pg rows below)
        try:
            coll_bytes = self.store.collections_bytes()  # one index pass
        except Exception:
            coll_bytes = {}
        for cid in self.store.list_collections():
            pool_id = None
            if "." in cid:
                try:
                    pool_id = int(cid.split(".", 1)[0])
                except ValueError:
                    pool_id = None
            try:
                n_here = sum(
                    1 for o in self.store.list_objects(cid)
                    if not o.startswith("_")
                )
            except Exception as e:
                # collection dropped concurrently (split cleanup) —
                # count what's still listable, but leave a trace
                self.cct.dout("osd", 10,
                              f"{self.whoami} stats skipped {cid}: {e!r}")
                continue
            coll_objects[cid] = n_here
            num_objects += n_here
            if pool_id is not None:
                pool_bytes[pool_id] = (
                    pool_bytes.get(pool_id, 0) + coll_bytes.get(cid, 0)
                )
                pool_objects[pool_id] = (
                    pool_objects.get(pool_id, 0) + n_here
                )
        self.logger.set("numpg", num_pgs)
        # per-PG status rows, PRIMARY-reported so each PG has exactly one
        # author (reference: pg_stat_t streamed inside MMgrReport)
        pg_info: dict[str, dict] = {}
        m = self.osdmap
        if m is not None:
            with self._pgs_lock:
                snapshot = list(self.pgs.values())
            for pg in snapshot:
                pool = m.pools.get(pg.pool_id)
                if pool is None:
                    continue
                try:
                    up, _upp, acting, prim = m.pg_to_up_acting_osds(
                        pg.pool_id, pg.ps)
                except (KeyError, IndexError, ValueError):
                    continue
                if prim != self.id:
                    continue
                # a PG that has never seen an interval CHANGE never runs
                # the peering round — activated_interval stays -1 from
                # birth.  That is healthy ONLY while interval_start is
                # still 0; once an interval change lands, -1 means the
                # first peering round hasn't finished and ops are being
                # refused (primary_ops gates on activated==interval_start)
                peered = (pg.activated_interval == pg.interval_start
                          or (pg.activated_interval < 0
                              and pg.interval_start == 0))
                # cephheal pg_stats: object count of the primary's own
                # shard collection (reusing the store walk above), plus
                # degraded/misplaced object-copy counts — down or
                # absent acting slots degrade every object LIVE (no
                # recovery pass needed to see a kill), and the recovery
                # pass's missing-on-live-peers count rides on top
                is_ec = pool.type == PG_POOL_ERASURE
                try:
                    my_shard = acting.index(self.id) if is_ec else 0
                except ValueError:
                    my_shard = 0
                n_obj = coll_objects.get(self._cid(pg.pgid, my_shard), 0)
                # missing copies = pool.size minus LIVE members: counts
                # both EC's positional -1 holes and replicated pools'
                # COMPACTED acting lists (a down replica is dropped
                # from acting entirely, never a -1 slot)
                live_members = sum(
                    1 for o in acting if o >= 0 and m.is_up(o))
                down_slots = max(0, pool.size - live_members)
                degraded = (n_obj * down_slots
                            + int(getattr(pg, "stat_degraded_peers", 0)))
                misplaced = n_obj * sum(
                    1 for a, u in zip(acting, up) if a != u)
                if peered:
                    if down_slots:
                        state = "active+degraded"
                    elif degraded:
                        state = "active+recovering+degraded"
                    else:
                        state = "active+clean"
                else:
                    state = "peering"
                pg_info[pg.pgid] = {
                    "state": state,
                    "version": pg.version,
                    "objects": n_obj,
                    "degraded": degraded,
                    "misplaced": misplaced,
                }
        try:
            self.messenger.connect((host, int(port))).send_message(
                MMgrReport(
                    daemon=self.whoami,
                    counters=self.cct.perf.dump(),
                    # counter docs/types ride along so the prometheus
                    # exporter emits real HELP text and histogram TYPEs
                    schema=self.cct.perf.schema(),
                    epoch=self.my_epoch(),
                    stats={"num_pgs": num_pgs, "num_objects": num_objects,
                           "pool_bytes": {
                               str(k): v for k, v in pool_bytes.items()
                           },
                           "pool_objects": {
                               str(k): v for k, v in pool_objects.items()
                           },
                           "statfs": self.store.statfs(),
                           # sticky count: in-flight slow PLUS recently
                           # completed slow (cephmeter — a straggler
                           # finishing between report polls must not
                           # vanish from SLOW_OPS before the digest
                           # samples it)
                           "slow_ops": self.op_tracker.slow_op_count(),
                           "slow_ops_detail":
                               self.op_tracker.slow_summaries(),
                           # accelerator health rides the same stream
                           # SLOW_OPS does: mgr digest -> mon _health
                           "backend_health": backend_health(),
                           # cephheal: PGs whose recovery pass has
                           # raised >= 3 consecutive ticks — surfaced
                           # in RECOVERY_STALLED instead of scrolling
                           # away at dout level 1
                           "recovery_failing": self._failing_pgs(),
                           "pg_info": pg_info},
                )
            )
        except (OSError, ConnectionError, ValueError):
            pass  # mgr down: retry next interval

    def _failing_pgs(self, threshold: int = 3) -> dict:
        """{pgid: {"count", "error"}} for PGs whose _recover_pg has
        raised `threshold`+ consecutive ticks (reset on a clean pass)."""
        with self._lock:
            return {
                pgid: {"count": ent[0], "error": ent[1]}
                for pgid, ent in self._recovery_failures.items()
                if ent[0] >= threshold
            }

    def _heartbeat(self) -> None:
        """Ping peers sharing PGs with us (reference: OSD::heartbeat);
        after osd_heartbeat_grace seconds of silence (grace/interval
        intervals) report the peer to the mon (§5.3)."""
        m = self.osdmap
        if m is None:
            return
        interval = float(self.cct.conf.get("osd_heartbeat_interval"))
        grace = float(self.cct.conf.get("osd_heartbeat_grace"))
        silent_limit = max(1, round(grace / max(interval, 1e-9)))
        peers: set[int] = set()
        with self._pgs_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                acting, _ = self._acting(pg.pool_id, pg.ps)
            except KeyError:
                continue
            peers |= {o for o in acting if o >= 0 and o != self.id}
        for osd in peers:
            if not m.is_up(osd):
                continue
            prev = self._hb_failures.get(osd, 0)
            try:
                self._conn_to_osd(osd).send_message(
                    MOSDPingMsg(op="ping", osd=self.id, epoch=self.my_epoch())
                )
                self._hb_failures[osd] = prev + 1
            except (OSError, ConnectionError):
                self._hb_failures[osd] = prev + 1
            if self._hb_failures.get(osd, 0) >= silent_limit:
                self.mc.report_failure(osd, failed_for=grace)
                # remember the report so a later ping reply retracts it
                # (MOSDAlive) instead of leaving a stale corroboration
                # entry on the leader
                self._hb_reported.add(osd)
                # restart the count: re-report only after another full
                # grace of silent intervals, not on every subsequent tick
                self._hb_failures.pop(osd, None)

