"""OSDMap analog — epoch-versioned pool/PG/OSD placement state.

Reference: src/osd/OSDMap.{h,cc} :: OSDMap, pg_pool_t (src/osd/osd_types.h).
The placement pipeline mirrored here is SURVEY.md §3.3's single-mapping call
stack:

    pg_to_up_acting_osds
      → _pg_to_raw_osds:  ps → pps placement seed (ceph_stable_mod +
                          crush_hash32_2, pg_pool_t::raw_pg_to_pps with the
                          modern FLAG_HASHPSPOOL behavior)
      → CrushWrapper::do_rule with the osd reweight vector
      → _apply_upmap:     pg_upmap / pg_upmap_items overrides
      → _raw_to_up_osds:  drop non-existent/down OSDs (compact for
                          replicated, positional ITEM_NONE holes for EC)
      → _apply_primary_affinity (hash-thinned primary pick)
      → pg_temp / primary_temp acting overrides

plus the batched sibling `map_pool` that runs the CRUSH descent for every PG
of a pool in one crush_do_rule_batch launch (the TPU path consumed by the
balancer and the osdmaptool analog, SURVEY.md §1 seam #2).

Provenance caveat (SURVEY.md §0): the reference mount was empty; semantics
are written from documented OSDMap behavior and enforced internally — the
scalar path and the batched path must agree exactly (tests/test_osdmap.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..crush import CrushWrapper, ITEM_NONE
from ..crush.hash import crush_hash32_2_np

#: pg_pool_t::TYPE_* (reference: src/osd/osd_types.h)
PG_POOL_REPLICATED = 1
PG_POOL_ERASURE = 3

#: osd_state bits (reference: src/osd/OSDMap.h CEPH_OSD_EXISTS/UP)
OSD_EXISTS = 1
OSD_UP = 2

#: 16.16 fixed-point unity (reference: CEPH_OSD_IN / MAX_PRIMARY_AFFINITY)
OSD_IN = 0x10000
MAX_PRIMARY_AFFINITY = 0x10000


def pg_num_mask(pg_num: int) -> int:
    """reference: pg_pool_t::calc_pg_masks — (1 << bits_of(pg_num-1)) - 1."""
    if pg_num <= 0:
        raise ValueError("pg_num must be positive")
    return (1 << (pg_num - 1).bit_length()) - 1


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """reference: src/include/rados.h :: ceph_stable_mod — stable modulo so
    growing pg_num splits PGs instead of reshuffling them."""
    return x & bmask if (x & bmask) < b else x & (bmask >> 1)


def _stable_mod_np(x: np.ndarray, b: int, bmask: int) -> np.ndarray:
    lo = x & np.uint32(bmask)
    return np.where(lo < b, lo, x & np.uint32(bmask >> 1))


def object_ps(oid: str, pg_num: int) -> int:
    """Object name -> placement seed (reference: ceph_str_hash + stable_mod
    in OSDMap::object_locator_to_pg).

    crc32c stands in for the rjenkins string hash: it is stable, fast, and
    shared with the C++ oracle; only stability matters for placement."""
    from ..common.crc32c import crc32c

    h = crc32c(oid.encode())
    return ceph_stable_mod(h, pg_num, pg_num_mask(pg_num))


@dataclass
class PGPool:
    """reference: src/osd/osd_types.h :: pg_pool_t (placement fields plus
    the pool-snapshot registry: snap_seq is the latest issued snap id,
    snaps maps live ids to names — reference: pg_pool_t::snaps/snap_seq)."""

    pool_id: int
    pg_num: int
    size: int
    crush_rule: int
    type: int = PG_POOL_REPLICATED
    min_size: int = 0
    pgp_num: int = 0  # 0 → pg_num
    ec_profile: str | None = None  # profile name for erasure pools
    name: str = ""
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)  # snapid -> name
    # cache tiering (reference: pg_pool_t::tier_of / read_tier /
    # write_tier / cache_mode / tiers).  A CACHE pool has tier_of >= 0
    # pointing at its base; the BASE pool lists its tiers and, once an
    # overlay is set, carries read_tier/write_tier so the Objecter
    # redirects client I/O to the cache (Objecter::_calc_target).
    tier_of: int = -1
    tiers: list = field(default_factory=list)
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = "none"  # none | writeback | readproxy
    # agent thresholds (reference: pg_pool_t::target_max_objects and the
    # TierAgentState full/evict effort derived from it)
    target_max_objects: int = 0
    # pool quotas (reference: pg_pool_t::quota_max_bytes/objects + the
    # FLAG_FULL_QUOTA the mon sets when stats cross them); `flags`
    # carries pool flags, e.g. "full_quota"
    quota_max_bytes: int = 0
    quota_max_objects: int = 0
    flags: list = field(default_factory=list)
    # enabled applications, app -> metadata (reference:
    # pg_pool_t::application_metadata + the POOL_APP_NOT_ENABLED check)
    application: dict = field(default_factory=dict)

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num
        if not self.min_size:
            # replicated: the reference's default write quorum is
            # size - size/2 (1 for size-2 pools — a degraded pair still
            # takes writes); EC keeps k (= size - 1 parity short)
            self.min_size = (
                self.size - self.size // 2
                if self.type == PG_POOL_REPLICATED else self.size - 1
            )
        if not self.name:
            self.name = f"pool{self.pool_id}"
        # JSON round-trips dict keys as strings
        self.snaps = {int(k): v for k, v in (self.snaps or {}).items()}
        # mutable fields must be COPIES: _pending()'s vars()/**kwargs
        # round-trip would otherwise alias the committed map's lists and
        # a failed proposal's mutation would leak into committed state
        self.flags = list(self.flags or [])
        self.tiers = list(self.tiers or [])
        self.application = dict(self.application or {})

    def raw_pg_to_pps(self, ps: int) -> int:
        """reference: pg_pool_t::raw_pg_to_pps, FLAG_HASHPSPOOL branch —
        hash the stable-modded seed with the pool id so co-sized pools
        don't stack their PGs on the same OSDs."""
        seed = ceph_stable_mod(ps, self.pgp_num, pg_num_mask(self.pgp_num))
        return int(crush_hash32_2_np(np.uint32(seed), np.uint32(self.pool_id)))

    def raw_pg_to_pps_batch(self, ps: np.ndarray) -> np.ndarray:
        seed = _stable_mod_np(
            np.asarray(ps, np.uint32), self.pgp_num, pg_num_mask(self.pgp_num)
        )
        return crush_hash32_2_np(seed, np.uint32(self.pool_id))


class OSDMap:
    """The cluster map: CRUSH + pools + per-OSD state + upmap overrides."""

    def __init__(self, crush: CrushWrapper, max_osd: int = 0):
        self.epoch = 1
        self.crush = crush
        self.max_osd = max_osd or crush.map.max_devices
        self.osd_state = [OSD_EXISTS | OSD_UP] * self.max_osd
        self.osd_weight = [OSD_IN] * self.max_osd  # in/out reweight, 16.16
        self.osd_primary_affinity = [MAX_PRIMARY_AFFINITY] * self.max_osd
        self.pools: dict[int, PGPool] = {}
        # highest pool id EVER allocated — never reused, so a deleted
        # pool's id cannot alias a later pool in collections/upmaps
        # (reference: OSDMap pool ids are monotonic)
        self.max_pool_id = 0
        # (pool, ps) → explicit raw mapping (reference: OSDMap pg_upmap)
        self.pg_upmap: dict[tuple[int, int], list[int]] = {}
        # (pool, ps) → [(from, to), ...] (reference: pg_upmap_items)
        self.pg_upmap_items: dict[tuple[int, int], list[tuple[int, int]]] = {}
        # acting-set overrides (reference: OSDMap pg_temp / primary_temp)
        self.pg_temp: dict[tuple[int, int], list[int]] = {}
        self.primary_temp: dict[tuple[int, int], int] = {}
        # osd -> (host, port) messenger address (reference: OSDMap
        # osd_addrs — how clients locate a mapped OSD)
        self.osd_addrs: dict[int, tuple[str, int]] = {}
        # cephx service-key GENERATIONS (reference: the rotating secrets
        # CephxKeyServer distributes — here each generation's key derives
        # deterministically from the cluster secret, so bumping the
        # generation IN THE MAP rotates every daemon atomically with the
        # map push and needs no key-distribution protocol)
        self.auth_gens: dict[str, int] = {}
        # cluster-wide flags, e.g. "noout"/"nodown" (reference: OSDMap
        # get_flags / CEPH_OSDMAP_NOOUT)
        self.flags: set[str] = set()
        # EC profiles live in the OSDMap, not daemon config (reference:
        # OSDMap::erasure_code_profiles; SURVEY.md §5.6)
        self.ec_profiles: dict[str, dict] = {}

    # -- state management --------------------------------------------------
    def create_pool(
        self,
        pool_id: int,
        pg_num: int,
        size: int,
        crush_rule: int,
        type: int = PG_POOL_REPLICATED,
        **kw,
    ) -> PGPool:
        """reference: OSDMonitor::prepare_new_pool (validation subset)."""
        if pool_id in self.pools:
            raise ValueError(f"pool {pool_id} exists")
        if crush_rule not in self.crush.map.rules:
            raise ValueError(f"no crush rule {crush_rule}")
        p = PGPool(pool_id, pg_num, size, crush_rule, type=type, **kw)
        self.pools[pool_id] = p
        self.max_pool_id = max(self.max_pool_id, pool_id)
        return p

    def is_up(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_state[osd] & OSD_UP)

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_state[osd] & OSD_EXISTS)

    def is_in(self, osd: int) -> bool:
        """reference: OSDMap::is_in — nonzero reweight."""
        return self.exists(osd) and self.osd_weight[osd] != 0

    def mark_down(self, osd: int) -> None:
        """reference: OSDMonitor failure handling — down keeps CRUSH weight;
        the PG maps elsewhere only once the OSD is also marked out."""
        self.osd_state[osd] &= ~OSD_UP
        self.epoch += 1

    def mark_up(self, osd: int) -> None:
        self.osd_state[osd] |= OSD_UP | OSD_EXISTS
        self.epoch += 1

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0
        self.epoch += 1

    def mark_in(self, osd: int) -> None:
        self.osd_weight[osd] = OSD_IN
        self.epoch += 1

    def set_primary_affinity(self, osd: int, aff: float) -> None:
        self.osd_primary_affinity[osd] = int(aff * MAX_PRIMARY_AFFINITY)
        self.epoch += 1

    # -- scalar mapping path (ground truth) --------------------------------
    def pg_to_raw_osds(self, pool: PGPool, ps: int) -> tuple[list[int], int]:
        """reference: OSDMap::_pg_to_raw_osds — CRUSH with the reweight
        vector; returns (raw osds, pps seed)."""
        pps = pool.raw_pg_to_pps(ps)
        raw = self.crush.do_rule(pool.crush_rule, pps, pool.size, self.osd_weight)
        return raw, pps

    def _upmap_valid_target(self, osd: int) -> bool:
        # reference: OSDMap::_apply_upmap — targets must exist and not be
        # marked out (weight 0), else the override is ignored.
        return self.exists(osd) and self.osd_weight[osd] != 0

    def _apply_upmap(self, pool: PGPool, ps: int, raw: list[int]) -> list[int]:
        """reference: OSDMap::_apply_upmap.  A pg_upmap vector whose length
        differs from the pool size is ignored (OSDMonitor rejects such
        entries at set time; tolerating them on load keeps the scalar and
        batch paths — whose output width is pool.size — in agreement)."""
        key = (pool.pool_id, ps)
        forced = self.pg_upmap.get(key)
        if (
            forced
            and len(forced) == pool.size
            and all(self._upmap_valid_target(o) for o in forced)
        ):
            raw = list(forced)
        items = self.pg_upmap_items.get(key)
        if items:
            raw = list(raw)
            for frm, to in items:
                if frm in raw and to not in raw and self._upmap_valid_target(to):
                    raw[raw.index(frm)] = to
        return raw

    def _raw_to_up_osds(self, pool: PGPool, raw: list[int]) -> list[int]:
        """reference: OSDMap::_raw_to_up_osds — drop down/non-existent OSDs:
        compact for replicated pools, positional NONE holes for EC (shard
        identity is positional, SURVEY.md §3.2)."""
        def ok(o: int) -> bool:
            return o >= 0 and self.exists(o) and self.is_up(o)

        if pool.type == PG_POOL_ERASURE:
            return [o if ok(o) else ITEM_NONE for o in raw]
        return [o for o in raw if ok(o)]

    def _apply_primary_affinity(self, pps: int, up: list[int]) -> int:
        """reference: OSDMap::_apply_primary_affinity — each up OSD in order
        keeps the primary role with probability affinity/0x10000, decided by
        a pps-seeded hash so the choice is deterministic per PG."""
        pos = -1
        for i, o in enumerate(up):
            if o < 0:
                continue
            a = self.osd_primary_affinity[o]
            if a < MAX_PRIMARY_AFFINITY and (
                int(crush_hash32_2_np(np.uint32(pps), np.uint32(o))) >> 16
            ) >= a:
                continue
            pos = i
            break
        if pos < 0:  # every candidate declined → fall back to first up OSD
            for i, o in enumerate(up):
                if o >= 0:
                    return o
            return ITEM_NONE
        return up[pos]

    def pg_to_up_acting_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int, list[int], int]:
        """reference: OSDMap::pg_to_up_acting_osds — returns
        (up, up_primary, acting, acting_primary)."""
        pool = self.pools[pool_id]
        raw, pps = self.pg_to_raw_osds(pool, ps)
        raw = self._apply_upmap(pool, ps, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._apply_primary_affinity(pps, up)
        acting = self.pg_temp.get((pool_id, ps)) or up
        acting_primary = self.primary_temp.get((pool_id, ps))
        if acting_primary is None:
            if acting is up:
                acting_primary = up_primary
            else:
                acting_primary = next((o for o in acting if o >= 0), ITEM_NONE)
        return up, up_primary, list(acting), acting_primary

    # -- batched mapping path (TPU) ----------------------------------------
    def map_pool(self, pool_id: int) -> tuple[np.ndarray, np.ndarray]:
        """Map every PG of a pool in one batched CRUSH launch.

        Returns (up [pg_num, size] with ITEM_NONE fill, up_primary [pg_num]).
        The CRUSH descent — HOT LOOP #3 — runs on device via
        crush_do_rule_batch; the sparse upmap/temp overrides and the up/
        affinity filters are cheap vectorized host post-passes, exactly the
        split SURVEY.md §3.3 prescribes for the batch consumers."""
        pool = self.pools[pool_id]
        ps = np.arange(pool.pg_num, dtype=np.uint32)
        pps = pool.raw_pg_to_pps_batch(ps)
        raw = np.asarray(
            self.crush.do_rule_batch(
                pool.crush_rule,
                pps.astype(np.int32),
                pool.size,
                self.osd_weight,
            )
        ).astype(np.int64)

        # sparse per-PG upmap overrides (dict-sized, not pg_num-sized work)
        for (pid, s), forced in self.pg_upmap.items():
            if (
                pid == pool_id
                and s < pool.pg_num
                and len(forced) == pool.size
                and all(self._upmap_valid_target(o) for o in forced)
            ):
                raw[s] = forced
        for (pid, s), items in self.pg_upmap_items.items():
            if pid != pool_id or s >= pool.pg_num:
                continue
            row = list(raw[s])
            for frm, to in items:
                if frm in row and to not in row and self._upmap_valid_target(to):
                    row[row.index(frm)] = to
            raw[s] = row

        # up filter (vectorized): valid = exists & up
        state = np.zeros(self.max_osd + 1, dtype=bool)
        state[:-1] = [
            (st & OSD_UP) and (st & OSD_EXISTS) for st in self.osd_state
        ]
        valid = (raw >= 0) & (raw < self.max_osd) & state[np.clip(raw, 0, self.max_osd)]
        if pool.type == PG_POOL_ERASURE:
            up = np.where(valid, raw, ITEM_NONE)
        else:
            # stable left-compaction of valid entries per row
            order = np.argsort(~valid, axis=1, kind="stable")
            up = np.where(
                np.take_along_axis(valid, order, axis=1),
                np.take_along_axis(raw, order, axis=1),
                ITEM_NONE,
            )

        up_primary = self._primary_batch(pps, up)
        return up.astype(np.int32), up_primary.astype(np.int32)

    def _primary_batch(self, pps: np.ndarray, up: np.ndarray) -> np.ndarray:
        aff = np.asarray(self.osd_primary_affinity + [0], dtype=np.int64)
        present = up >= 0
        if all(a == MAX_PRIMARY_AFFINITY for a in self.osd_primary_affinity):
            accept = present
        else:
            osd_aff = aff[np.clip(up, 0, self.max_osd)]
            h = (
                crush_hash32_2_np(
                    pps[:, None].astype(np.uint32), up.astype(np.uint32)
                ).astype(np.int64)
                >> 16
            )
            accept = present & ((osd_aff >= MAX_PRIMARY_AFFINITY) | (h < osd_aff))
        # first accepted, else first present, else NONE
        def first(mask):
            idx = np.argmax(mask, axis=1)
            ok = mask.any(axis=1)
            return np.where(ok, up[np.arange(len(up)), idx], ITEM_NONE), ok

        prim_a, ok_a = first(accept)
        prim_p, _ = first(present)
        return np.where(ok_a, prim_a, prim_p)

    # -- serialization (osdmaptool surface) --------------------------------
    def to_json(self) -> dict:
        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "osd_state": list(self.osd_state),
            "osd_weight": list(self.osd_weight),
            "osd_primary_affinity": list(self.osd_primary_affinity),
            "crush_text": self.crush.format_text(),
            # legacy aux tables VERBATIM (advisor r3 / r4 verdict #5):
            # the text format cannot carry straw scaling factors or tree
            # node weights, and re-deriving them on every decode would
            # silently replace tables an ingested map computed under a
            # different straw_calc_version — changing placements across
            # a mon restart.  Reference: crush wire encoding carries the
            # bucket aux arrays; straw_calc_version only governs builds.
            "crush_aux": {
                str(bid): {
                    "straws": list(b.straws),
                    "node_weights": list(b.node_weights),
                }
                for bid, b in self.crush.map.buckets.items()
                if b.straws or b.node_weights
            },
            "pools": [vars(p) for p in self.pools.values()],
            "max_pool_id": self.max_pool_id,
            "pg_upmap": [
                {"pool": k[0], "ps": k[1], "osds": v}
                for k, v in self.pg_upmap.items()
            ],
            "pg_upmap_items": [
                {"pool": k[0], "ps": k[1], "mappings": [list(m) for m in v]}
                for k, v in self.pg_upmap_items.items()
            ],
            "pg_temp": [
                {"pool": k[0], "ps": k[1], "osds": v}
                for k, v in self.pg_temp.items()
            ],
            "primary_temp": [
                {"pool": k[0], "ps": k[1], "osd": v}
                for k, v in self.primary_temp.items()
            ],
            "osd_addrs": [
                {"osd": o, "host": a[0], "port": a[1]}
                for o, a in self.osd_addrs.items()
            ],
            "flags": sorted(self.flags),
            "ec_profiles": self.ec_profiles,
            "auth_gens": self.auth_gens,
        }

    @classmethod
    def from_json(cls, d: dict) -> "OSDMap":
        m = cls(CrushWrapper.parse_text(d["crush_text"]), d["max_osd"])
        # restore ingested aux tables verbatim over the parser's
        # re-derived ones (see to_json): length-checked so a corrupt
        # record falls back to the derived tables instead of crashing
        # the mapper later
        for bid_s, aux in (d.get("crush_aux") or {}).items():
            try:
                b = m.crush.map.buckets.get(int(bid_s))
                if b is None or not isinstance(aux, dict):
                    continue
                straws = aux.get("straws") or []
                if straws and len(straws) == len(b.items):
                    b.straws = [int(s) for s in straws]
                nodes = aux.get("node_weights") or []
                # structural validity: a tree's node array length is a
                # power of two covering 2*size leaves — anything else
                # would start descent at an odd root and collapse every
                # draw onto one item
                n = len(nodes)
                if (nodes and n >= 2 * len(b.items)
                        and n & (n - 1) == 0):
                    b.node_weights = [int(x) for x in nodes]
            except (TypeError, ValueError, AttributeError):
                continue  # corrupt record: keep the derived tables
        m.epoch = d.get("epoch", 1)
        m.osd_state = list(d["osd_state"])
        m.osd_weight = list(d["osd_weight"])
        m.osd_primary_affinity = list(d["osd_primary_affinity"])
        for pd in d["pools"]:
            m.pools[pd["pool_id"]] = PGPool(**pd)
        m.max_pool_id = max(int(d.get("max_pool_id", 0)),
                            max(m.pools, default=0))
        for e in d.get("pg_upmap", []):
            m.pg_upmap[(e["pool"], e["ps"])] = list(e["osds"])
        for e in d.get("pg_upmap_items", []):
            m.pg_upmap_items[(e["pool"], e["ps"])] = [
                tuple(x) for x in e["mappings"]
            ]
        for e in d.get("pg_temp", []):
            m.pg_temp[(e["pool"], e["ps"])] = list(e["osds"])
        for e in d.get("primary_temp", []):
            m.primary_temp[(e["pool"], e["ps"])] = e["osd"]
        for e in d.get("osd_addrs", []):
            m.osd_addrs[e["osd"]] = (e["host"], e["port"])
        m.flags = set(d.get("flags", []))
        m.ec_profiles = dict(d.get("ec_profiles", {}))
        m.auth_gens = dict(d.get("auth_gens", {}))
        return m
