"""EC writes, reads-with-reconstruct, and partial-stripe RMW parity deltas (reference: src/osd/ECBackend.cc, src/osd/ECTransaction.cc).

Split out of osd/daemon.py (round-4 verdict item #6) — the methods
are verbatim; `OSD` composes every mixin, so cross-mixin calls (e.g.
the tier front-end invoking the replicated backend) resolve on self.
"""
from __future__ import annotations



import time

import numpy as np

from ..common.crc32c import crc32c
from ..common.failpoint import FailpointCrash, FailpointError, failpoint
from ..common.tracer import TRACER, op_trace, trace_now
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MECSubOpWrite,
    MOSDOp,
    MOSDOpReply,
    pack_data,
    unpack_data,
)
from .pg import CLONE_SEP, PGState, _current_generation
from .pg_log import LogEntry


class ECBackendMixin:
    # .. coalesced encode (osd/write_batcher.py) ...........................
    def _batch_matrix(self, codec):
        """(coding matrix, stable digest) IF the codec's encode is a
        plain byte-column-local GF matrix apply with identity chunk
        placement — the property (same one the RMW parity delta rests
        on) under which stripes from DIFFERENT ops can be fused along
        the column axis and encoded in one batch.  (None, None) = not
        batchable: sub-chunked (CLAY), packet/bitmatrix, remapped (LRC)
        codecs, and the non-jax referee backends, all encode inline.

        The digest (ops.bitplane.matrix_digest) is computed ONCE and
        cached on the codec object — the batcher and the device
        bitmatrix cache key by it instead of a fresh per-stripe
        ``mat.tobytes()`` host copy (the cephdma satellite fix)."""
        if getattr(codec, "backend", "jax") != "jax":
            # oracle/numpy referee backends keep their own encode path
            # (parity provenance for the cross-backend equality tests);
            # plugins without the attr (shec) are jax-native
            return None, None
        try:
            if not codec.supports_parity_delta():
                return None, None
            if codec.get_sub_chunk_count() != 1:
                return None, None
        except (AttributeError, NotImplementedError):
            return None, None
        mat = getattr(codec, "coding", None)
        if not isinstance(mat, np.ndarray):
            return None, None
        key = getattr(codec, "_coding_digest", None)
        if key is None:
            from ..ops.bitplane import matrix_digest

            key = matrix_digest(mat)
            try:
                codec._coding_digest = key
            except (AttributeError, TypeError):
                pass  # frozen codec object: recompute per call
        return mat, key

    def _ec_encode_chunks(self, codec, chunks):
        """encode_chunks through the write batcher when eligible
        (coalesced with concurrent ops' stripes), codec-inline
        otherwise; parity bytes identical either way."""
        batcher = getattr(self, "write_batcher", None)
        mat, mat_key = self._batch_matrix(codec)
        if batcher is None or mat is None:
            t0 = trace_now()
            out = codec.encode_chunks(chunks)
            self._op_stage("encode", t0, trace_now(), codec_inline=True)
            return out
        return batcher.encode_chunks(mat, chunks, mat_key=mat_key)

    def _ec_encode(self, codec, data: bytes) -> dict:
        """Full-stripe encode for _ec_write: same chunk dict as
        ``codec.encode(set(range(n)), data)``, with the parity matmul
        routed through the write batcher when the codec is batchable."""
        n = codec.get_chunk_count()
        batcher = getattr(self, "write_batcher", None)
        mat, mat_key = self._batch_matrix(codec)
        if batcher is None or mat is None:
            t0 = trace_now()
            enc = codec.encode(set(range(n)), data)
            self._op_stage("encode", t0, trace_now(), codec_inline=True)
            return enc
        k = codec.get_data_chunk_count()
        L = codec.get_chunk_size(len(data))
        chunks = codec.encode_prepare(data, L)
        parity = batcher.encode_chunks(mat, chunks, mat_key=mat_key)
        enc = {i: chunks[i] for i in range(k)}
        for j in range(parity.shape[0]):
            enc[k + j] = parity[j]
        return enc

    # .. EC pool ...........................................................
    def _ec_op(self, pg: PGState, pool, acting: list[int], msg: MOSDOp):
        codec = self._codec_for_pool(pool)
        my_shard = acting.index(self.id)
        if msg.op in ("write_full", "write", "append", "delete"):
            # min_size gate BEFORE any mutation (reference: PrimaryLogPG
            # refuses ops while acting < pool.min_size): refusing up front
            # both protects durability (never take a write we may not be
            # able to re-protect) and keeps -EAGAIN retries side-effect
            # free — a partially-applied-then-refused write would make
            # the client resend double-apply
            reachable = sum(
                1 for o in acting
                if o >= 0 and (o == self.id or self.osdmap.is_up(o))
            )
            if reachable < pool.min_size:
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"{reachable} acting shards reachable < "
                           f"min_size {pool.min_size}",
                )
        if msg.op == "write_full":
            data = unpack_data(msg.data) or b""
            with pg.lock:
                return self._ec_write(
                    pg, pool, codec, acting, my_shard, msg, data
                )
        if msg.op in ("write", "append"):
            data = unpack_data(msg.data) or b""
            with pg.lock:
                return self._ec_rmw(
                    pg, pool, codec, acting, my_shard, msg, data
                )
        if msg.op == "read":
            return self._ec_read(pg, codec, acting, msg)
        if msg.op == "delete":
            with pg.lock:
                return self._ec_delete(pg, acting, my_shard, msg)
        if msg.op == "stat":
            try:
                size = int(
                    self.store.getattr(
                        self._cid(pg.pgid, my_shard), msg.oid, "size"
                    )
                )
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(),
                                   result={"size": size, "version": pg.version})
            except (NotFound, KeyError):
                return MOSDOpReply(tid=msg.tid, retval=-2,
                                   epoch=self.my_epoch(), result="not found")
        if msg.op == "list":
            oids = sorted(
                o for o in self.store.list_objects(self._cid(pg.pgid, my_shard))
                if not o.startswith("_") and CLONE_SEP not in o
            )
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"oids": oids})
        if msg.op in ("setxattr", "getxattrs"):
            return self._xattr_op(pg, acting, my_shard, msg)
        if msg.op.startswith("omap_") or msg.op == "exec":
            # reference parity: EC pools support neither omap nor the
            # omap-backed object classes
            # (PrimaryLogPG::do_osd_ops returns -EOPNOTSUPP)
            return MOSDOpReply(tid=msg.tid, retval=-95,
                               epoch=self.my_epoch(),
                               result=f"{msg.op} not supported on EC pools")
        if msg.op in ("watch", "unwatch", "notify"):
            return self._watch_op(pg, pool, msg)
        return MOSDOpReply(tid=msg.tid, retval=-22, epoch=self.my_epoch(),
                           result=f"bad op {msg.op}")

    # .. partial-stripe RMW ................................................
    def _ec_object_size(self, pg, acting, oid: str):
        """Stored object size (the `size` xattr), local shard preferred,
        else reachable peers' metadata probes.  Returns an int, "absent"
        (a shard DEFINITIVELY reported no such object), or "unknown"
        (nobody answered either way — e.g. transient connection faults).
        The distinction matters: treating unreachable as absent would
        let a ranged write re-create an existing object as zeros."""
        for shard, osd in enumerate(acting):
            if osd != self.id:
                continue
            try:
                return int(self.store.getattr(
                    self._cid(pg.pgid, shard), oid, "size"))
            except (NotFound, KeyError, ValueError):
                break
        verdict = "unknown"
        best_size = None
        best_ver = -1
        for shard, osd in enumerate(acting):
            if osd < 0 or osd == self.id or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                                 offsets=[], epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                continue
            rep = self._wait_reply(tid)
            if rep is None:
                continue
            if rep.retval == 0 and rep.size is not None:
                # prefer the NEWEST-generation shard's size: a stale
                # shard that missed the last append would hand back the
                # old size and the append would overwrite live bytes
                v = getattr(rep, "ver", None)
                if v is None:
                    v = 0
                if v > best_ver or best_size is None:
                    best_ver, best_size = v, int(rep.size)
            elif rep.retval == -2:
                verdict = "absent"  # a live shard is sure it isn't there
        if best_size is not None:
            return best_size
        return verdict

    def _fetch_shard_range(self, pg, acting, shard: int, oid: str,
                           off: int, ln: int):
        """(`ln` bytes at `off` of one shard's stored chunk, that shard's
        stored per-object version) — local or via a ranged MECSubOpRead.
        (None, None) = holder down / chunk missing / short read."""
        osd = acting[shard] if shard < len(acting) else -1
        if osd == self.id:
            cid = self._cid(pg.pgid, shard)
            try:
                b = self.store.read(cid, oid, off, ln)
            except (NotFound, KeyError):
                return None, None
            return (bytes(b), self._stored_ver(cid, oid)) \
                if len(b) == ln else (None, None)
        if osd < 0 or not self.osdmap.is_up(osd):
            return None, None
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                             offsets=[[off, ln]], epoch=self.my_epoch())
            )
        except (OSError, ConnectionError):
            return None, None
        rep = self._wait_reply(tid)
        if rep is None or rep.retval != 0:
            return None, None
        b = unpack_data(rep.data) or b""
        return (b, rep.ver) if len(b) == ln else (None, None)

    def _rb_fetch_ranges(self, pg, acting, my_shard: int, oid: str,
                         wants: list[tuple[int, int, int]]):
        """Coalesced `_fetch_shard_range` for many shards at once:
        {shard: (bytes, ver) | (None, None)} via one read-batcher
        gather, or None when the batcher is absent/not coalescing/
        failed (caller falls back to the per-shard path).  Same
        contract as `_fetch_shard_range`: a short or missing range is
        (None, None)."""
        rb = getattr(self, "read_batcher", None)
        if not wants or rb is None or not rb.coalescing():
            return None
        from .read_batcher import ReadReq

        reqs = [ReadReq(j, oid, o, ln) for j, o, ln in wants]
        try:
            res = rb.gather(pg.pgid, acting, reqs,
                            est_bytes=sum(ln for _, _, ln in wants))
        except Exception as e:
            self.cct.dout("osd", 1,
                          f"{self.whoami} batched range fetch failed, "
                          f"per-shard fallback: {e!r}")
            return None
        out: dict[int, tuple] = {}
        for i, (j, _o, ln) in enumerate(wants):
            row = res.get(i)
            if row is None or row[0] is None or len(row[0]) != ln:
                out[j] = (None, None)
            else:
                out[j] = (row[0], row[1])
        return out

    def _read_cache_invalidate(self, pgid, oid: str) -> None:
        """cephread write-path hook: drop the hot-object cache entry a
        mutation just superseded (the version-bump invalidation)."""
        rc = getattr(self, "read_cache", None)
        if rc is not None:
            rc.invalidate((pgid, oid))

    def _stored_ver(self, cid: str, oid: str) -> int | None:
        """Per-object version xattr (object_info_t analog); None =
        unversioned (legacy object or backfill-pushed wildcard)."""
        try:
            v = self.store.getattr(cid, oid, "ver")
        except (NotFound, KeyError):
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            return None

    def _rmw_apply_local(self, t: Transaction, cid: str, oid: str,
                         full: bytearray, off: int, payload: bytes,
                         xor: bool) -> None:
        """Splice (xor=False) or GF-XOR (xor=True) `payload` into the
        primary's own pre-validated chunk bytes `full` at `off`, keeping
        the hinfo CRC current."""
        if xor:
            seg = (
                np.frombuffer(bytes(full[off:off + len(payload)]), np.uint8)
                ^ np.frombuffer(payload, np.uint8)
            ).tobytes()
        else:
            seg = payload
        full[off:off + len(seg)] = seg
        t.write(cid, oid, off, seg)
        t.setattr(cid, oid, "hinfo", str(crc32c(bytes(full))).encode())

    def _ec_full_splice(self, pg, pool, codec, acting, my_shard, msg,
                        data: bytes, off: int, size) -> MOSDOpReply:
        """RMW slow path: read the whole (possibly degraded) object,
        splice, re-encode everything via the full-object write.  Used when
        the write grows the stripe, the codec is sub-chunked (CLAY), or an
        affected shard's old bytes are unreachable (reconstruction needed).
        """
        old = b""
        if size:
            rd = self._ec_read(pg, codec, acting, MOSDOp(
                tid=self._next_tid(), pool=msg.pool, oid=msg.oid, op="read",
                epoch=self.my_epoch(), ps=pg.ps,
            ))
            if rd.retval != 0:
                # the current generation is temporarily sourceless
                # (unfound-pending): refuse retryably — serving/splicing
                # a stale base would launder a rollback into a fresh
                # version (reference: ops wait on missing objects)
                return MOSDOpReply(
                    tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                    result=f"rmw base unreadable now: {rd.result}",
                )
            old = unpack_data(rd.data) or b""
        buf = bytearray(max(len(old), off + len(data)))
        buf[:len(old)] = old
        buf[off:off + len(data)] = data
        return self._ec_write(pg, pool, codec, acting, my_shard, msg,
                              bytes(buf))

    def _ec_rmw(self, pg, pool, codec, acting, my_shard, msg,
                data: bytes) -> MOSDOpReply:
        """Ranged write / append on an EC object (reference:
        src/osd/ECTransaction.cc :: generate_transactions — the RMW that
        reads the old stripe remainder and re-encodes the touched stripes;
        expressed here as a PARITY-DELTA update, the optimized-EC
        formulation, which is also the TPU-shaped one: the parity delta is
        one GF matrix apply over just the touched column window).

        Correctness rests on GF-linearity of every registered plugin's
        encode_chunks: parity(new) = parity(old) XOR parity(delta), column
        by column.  Shards that would fuse stale bytes with the delta
        refuse the sub-op (version-jump guard in _handle_sub_write) and
        are rebuilt by log-delta recovery instead."""
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        size = self._ec_object_size(pg, acting, msg.oid)
        if size == "unknown":
            # can't tell whether the object exists (transient faults):
            # refusing retryably is the only safe answer — guessing
            # "absent" would zero-fill over live data
            return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                               result="object existence unknown (peers "
                                      "unreachable)")
        if size == "absent":
            size = None
        off = (size or 0) if msg.op == "append" else int(msg.off or 0)
        if not data:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version})
        end = off + len(data)
        if size is None:
            # object doesn't exist yet: a ranged write below `off` reads
            # back as zeros (reference: sparse write semantics)
            return self._ec_write(pg, pool, codec, acting, my_shard, msg,
                                  b"\x00" * off + data)
        L = codec.get_chunk_size(size) if size else 0
        sub_chunks = 1
        try:
            sub_chunks = codec.get_sub_chunk_count()
        except (AttributeError, NotImplementedError):
            pass  # plugin predates the sub-chunk API: classic layout
        try:
            delta_ok = bool(codec.supports_parity_delta())
        except (AttributeError, NotImplementedError):
            delta_ok = False
        if size == 0 or end > k * L or sub_chunks != 1 or not delta_ok:
            # codecs whose encode is not byte-column-local (bitmatrix
            # packet techniques, CLAY sub-chunks, LRC remapping) re-encode
            # the full stripe — a windowed delta would corrupt parity
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        # local pre-validation: the delta fast path needs the primary's
        # own chunk present, rot-free, and version-stamped — the stamp is
        # the authoritative old object version every other shard must
        # match (the primary serialized all prior writes)
        cid = self._cid(pg.pgid, my_shard)
        try:
            my_chunk = bytearray(self.store.read(cid, msg.oid))
        except (NotFound, KeyError):
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        my_ver = self._stored_ver(cid, msg.oid)
        try:
            stored_h = int(self.store.getattr(cid, msg.oid, "hinfo"))
        except (NotFound, KeyError, ValueError):
            stored_h = None
        floor = pg.log.obj_newest.get(msg.oid)
        if (
            my_ver is None
            or (floor is not None and my_ver < floor)
            or len(my_chunk) != L
            or (stored_h is not None and crc32c(bytes(my_chunk)) != stored_h)
        ):
            # unversioned legacy object, unexpected chunk length, or
            # local rot (full-splice reads exclude the rotted chunk and
            # the re-encode heals it)
            return self._ec_full_splice(pg, pool, codec, acting, my_shard,
                                        msg, data, off, size)
        # per-data-shard touched segments: shard j holds object bytes
        # [j*L, (j+1)*L) (contiguous-split layout, ErasureCode.encode_prepare)
        segs: dict[int, tuple[int, bytes]] = {}
        for j in range(k):
            lo, hi = max(off, j * L), min(end, (j + 1) * L)
            if lo < hi:
                segs[j] = (lo - j * L, data[lo - off:hi - off])
        c0 = min(o for o, _ in segs.values())
        c1 = max(o + len(b) for o, b in segs.values())
        w = c1 - c0
        old: dict[int, bytes] = {}
        # cephread: the remote old-byte fetches ride the read batcher
        # when it is coalescing — concurrent RMWs' ranged reads fuse
        # into the flush's single sub-op fan-out (historically this
        # loop paid one round trip PER SHARD PER OP)
        batched = self._rb_fetch_ranges(
            pg, acting, my_shard, msg.oid,
            [(j, o, len(b)) for j, (o, b) in segs.items() if j != my_shard],
        )
        for j, (o, b) in segs.items():
            if j == my_shard:
                old[j] = bytes(my_chunk[o:o + len(b)])
                continue
            if batched is not None:
                ob, over = batched.get(j, (None, None))
            else:
                ob, over = self._fetch_shard_range(
                    pg, acting, j, msg.oid, o, len(b)
                )
            if ob is None or over != my_ver:
                # unreachable, or the holder is a STALE generation whose
                # old bytes would poison the parity delta (the retry-
                # after-partial-apply case): reconstruct via the decode
                # slow path instead, which filters by version
                return self._ec_full_splice(pg, pool, codec, acting,
                                            my_shard, msg, data, off, size)
            old[j] = ob
        # parity delta = encode_chunks(delta window): zero rows for
        # untouched shards, new^old for touched ones; padded to the
        # codec's alignment (zero delta => zero parity delta, trim back)
        W = codec.get_chunk_size(k * w)
        delta = np.zeros((k, W), np.uint8)
        for j, (o, b) in segs.items():
            delta[j, o - c0:o - c0 + len(b)] = (
                np.frombuffer(b, np.uint8) ^ np.frombuffer(old[j], np.uint8)
            )
        parity_delta = np.asarray(
            self._ec_encode_chunks(codec, delta), np.uint8
        )[:, :w]
        new_size = max(size, end)
        version = pg.version + 1
        entry = LogEntry(version, "modify", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        wire_entry = entry.to_list()
        tids: dict[int, int] = {}
        # subop span opens BEFORE the fan-out (sub-ops carry its id as
        # parent); see object_ops._ec_write
        sub_span = TRACER.begin(self._op_trace_ctx(), "subop",
                                entity=self.whoami, rmw=True) \
            if TRACER.enabled else None
        t_sub0 = sub_span.t0 if sub_span is not None else trace_now()
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0 or not self.osdmap.is_up(osd):
                continue
            if shard in segs:
                mode, moff, payload = "range", segs[shard][0], segs[shard][1]
            elif shard >= k:
                mode, moff = "delta", c0
                payload = parity_delta[shard - k].tobytes()
            else:
                mode, moff, payload = None, None, None  # entry+size only
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=pack_data(payload) if payload is not None
                        else None,
                        crc=crc32c(payload) if payload is not None else None,
                        version=version, entry=wire_entry,
                        epoch=self.my_epoch(), mode=mode, off=moff,
                        over=my_ver, osize=new_size,
                        trace_id=(sub_span.trace_id
                                  if sub_span is not None else None),
                        parent_span=(sub_span.span_id
                                     if sub_span is not None else None),
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
                self.mc.report_failure(osd)
        t = Transaction()
        t.try_create_collection(cid)
        if my_shard in segs:
            o, b = segs[my_shard]
            self._rmw_apply_local(t, cid, msg.oid, my_chunk, o, b, xor=False)
        elif my_shard >= k:
            self._rmw_apply_local(
                t, cid, msg.oid, my_chunk, c0,
                parity_delta[my_shard - k].tobytes(), xor=True,
            )
        t.setattr(cid, msg.oid, "size", str(new_size).encode())
        t.setattr(cid, msg.oid, "ver", str(version).encode())
        self._log_txn(t, cid, pg, entry)
        t_c0 = trace_now()
        self.store.queue_transaction(t)
        self._read_cache_invalidate(pg.pgid, msg.oid)
        self._op_stage("commit", t_c0, trace_now(), version=version)
        a, deposed, failed = self._collect_subop_acks(tids, acting)
        self._op_stage("subop", t_sub0, trace_now(), span=sub_span,
                       fanout=len(tids), acked=a)
        acked = 1 + a
        for osd in failed:
            self.mc.report_failure(osd)
        if deposed and acked < pool.min_size:
            # deposed mid-op below quorum: the local apply is a FORK in a
            # dead interval — never acked, never answered as a dup
            # (_record_reqid marks the reqid "forked" so the resend
            # re-executes on the real primary).  At >= min_size the op
            # is durable in THIS interval despite the stray -116 (e.g. a
            # peer that just rebooted): ack it normally below.
            return MOSDOpReply(tid=msg.tid, retval=-116,
                               epoch=self.my_epoch(),
                               result={"deposed": True})
        if acked >= pool.min_size:
            return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                               result={"version": pg.version, "acked": acked})
        # structured under-ack refusal: the op IS applied+logged locally;
        # "applied" lets dup detection refuse re-execution on the resend
        return MOSDOpReply(tid=msg.tid, retval=-11, epoch=self.my_epoch(),
                           result={"applied": pg.version, "acked": acked,
                                   "error": "below min_size commits"})

    def _ec_delete(self, pg, acting, my_shard, msg) -> MOSDOpReply:
        version = pg.version + 1
        entry = LogEntry(version, "delete", msg.oid,
                         reqid=getattr(msg, "reqid", None))
        tids: dict[int, int] = {}
        for shard, osd in enumerate(acting):
            if shard == my_shard or osd < 0 or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpWrite(
                        tid=tid, pgid=pg.pgid, oid=msg.oid, shard=shard,
                        data=None, crc=None, version=version,
                        entry=entry.to_list(), epoch=self.my_epoch(),
                    )
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        cid = self._cid(pg.pgid, my_shard)
        t = Transaction()
        t.try_create_collection(cid)
        try:
            self.store.stat(cid, msg.oid)
            t.remove(cid, msg.oid)
        except (NotFound, KeyError):
            pass
        self._log_txn(t, cid, pg, entry)
        self.store.queue_transaction(t)
        self._read_cache_invalidate(pg.pgid, msg.oid)
        for tid in tids:
            self._wait_reply(tid)
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           result={"version": pg.version})

    def _gather_chunks(
        self, pg, codec, acting, oid: str, want: set[int],
        sizes: dict[int, int] | None = None,
        vers: dict[int, int | None] | None = None,
        stray: bool = False,
        floor: int | None = None,
    ) -> dict[int, bytes]:
        """Fetch chunk bytes for shard ids in `want` (local or remote).
        `sizes`, if given, collects the object-size xattr each replying
        shard reports (for padding-strip when the primary has no copy);
        `vers` likewise collects each shard's stored per-object version
        (None = wildcard) for stale-generation filtering.  `stray` also
        probes non-acting locations for shards the acting map cannot
        serve (see _gather_stray_chunks)."""
        got: dict[int, bytes] = {}
        tids: dict[int, int] = {}
        for shard in sorted(want):
            osd = acting[shard] if shard < len(acting) else -1
            if osd == self.id:
                cid = self._cid(pg.pgid, shard)
                try:
                    # same injection surface a remote shard read passes
                    # through (_handle_sub_read): a primary's own chunk
                    # can report EIO too
                    failpoint("osd.ec.shard_read", cct=self.cct,
                              entity=self.whoami, pgid=pg.pgid,
                              shard=shard, oid=oid)
                    chunk = self.store.read(cid, oid)
                except FailpointCrash:
                    raise
                except (FailpointError, NotFound, KeyError):
                    continue
                try:
                    stored = int(self.store.getattr(cid, oid, "hinfo"))
                except (NotFound, KeyError, ValueError):
                    stored = None
                if stored is not None and crc32c(chunk) != stored:
                    # rotted local chunk counts as missing: reconstruct
                    # from peers rather than decode garbage (hinfo read
                    # check, as in _handle_sub_read)
                    self.cct.dout(
                        "osd", 0,
                        f"{self.whoami} hinfo mismatch on local read "
                        f"{pg.pgid}/{oid} shard {shard}",
                    )
                    continue
                got[shard] = chunk
                if vers is not None:
                    vers[shard] = self._stored_ver(cid, oid)
                continue
            if osd < 0 or not self.osdmap.is_up(osd):
                continue
            tid = self._next_tid()
            tids[tid] = shard
            try:
                self._conn_to_osd(osd).send_message(
                    MECSubOpRead(tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                                 offsets=None, epoch=self.my_epoch())
                )
            except (OSError, ConnectionError):
                tids.pop(tid, None)
        for tid, shard in tids.items():
            rep = self._wait_reply(tid)
            if rep is not None and rep.retval == 0:
                got[shard] = unpack_data(rep.data)
                if sizes is not None and rep.size is not None:
                    sizes[shard] = int(rep.size)
                if vers is not None:
                    vers[shard] = getattr(rep, "ver", None)
        if stray:
            self._stray_upgrade(pg, oid, want, got, sizes, vers, acting,
                                floor)
        return got

    def _stray_upgrade(self, pg, oid: str, want: set[int], got: dict,
                       sizes, vers, acting,
                       floor: int | None = None) -> None:
        """Hunt NON-acting locations (reference: PeeringState's
        missing_loc — recovery reads from any OSD known to hold the
        object, not just the acting set) for two cases an acting
        permutation creates:
        - a shard with NO chunk at all (its new holder never held the
          role) — any copy helps;
        - a shard whose acting chunk is a STALE generation — only a
          copy stamped at (or above) the newest generation seen helps,
          and crucially the stale chunk must NOT suppress the hunt, or
          a current stray that could complete the stripe stays
          invisible and reads fail with too-few chunks.
        Iterates because finding a higher generation can reclassify
        previously-accepted chunks as stale."""
        for _round in range(3):
            present = [v for v in vers.values() if v is not None]
            if floor is not None:
                present.append(floor)
            target = max(present) if present else None
            needs = {
                sh: (target if sh in got else None)
                for sh in sorted(want)
                if sh not in got
                or (target is not None and vers.get(sh) is not None
                    and vers[sh] < target)
            }
            if not needs:
                return
            found = self._probe_strays(pg, oid, needs, acting)
            if not found:
                return
            for shard, (data, ver, size) in found.items():
                got[shard] = data
                if vers is not None:
                    vers[shard] = ver
                if sizes is not None and size is not None:
                    sizes[shard] = size

    PROBE_TIMEOUT = 3.0   # shared deadline for the metadata wave
    FETCH_TIMEOUT = 5.0   # shared deadline for the chunk-fetch wave
    PROBES_PER_SHARD = 16  # bound the walk on big maps (client-path cost)

    def _stray_local(self, pg, oid: str, shard: int, acting,
                     min_ver: int | None):
        """This OSD's own non-acting copy of a shard, if qualifying."""
        holder = acting[shard] if shard < len(acting) else -1
        if holder == self.id:  # acting-local was already tried
            return None
        cid = self._cid(pg.pgid, shard)
        try:
            chunk = self.store.read(cid, oid)
        except (NotFound, KeyError):
            return None
        try:
            stored = int(self.store.getattr(cid, oid, "hinfo"))
        except (NotFound, KeyError, ValueError):
            stored = None
        ver = self._stored_ver(cid, oid)
        if (
            (stored is None or crc32c(chunk) == stored)
            and (min_ver is None or (ver is not None and ver >= min_ver))
        ):
            size = None
            try:
                size = int(self.store.getattr(cid, oid, "size"))
            except (NotFound, KeyError, ValueError):
                pass
            return bytes(chunk), ver, size
        return None

    def _send_stray_read(self, pg, oid: str, shard: int, osd: int,
                         metadata: bool, tids: dict, key) -> None:
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(MECSubOpRead(
                tid=tid, pgid=pg.pgid, oid=oid, shard=shard,
                offsets=[] if metadata else None, epoch=self.my_epoch(),
            ))
            tids[tid] = key
        except (OSError, ConnectionError):
            pass

    def _probe_strays(self, pg, oid: str, needs: dict, acting) -> dict:
        """Find qualifying non-acting copies for many shards at once.
        `needs` maps shard -> min_ver (None = any copy qualifies,
        wildcard stamp included; numeric = only a copy with a NUMERIC
        generation >= min_ver).  Returns {shard: (data, ver, size)}.

        Advisor-r4 rework: every network step is a WAVE under one shared
        deadline — worst case is one probe timeout plus one fetch
        timeout, not 16 probes x 3 s x shards.  A per-PG stray-location
        cache (pg.stray_loc, the missing_loc analog) lets repeat
        degraded reads skip the probe wave entirely."""
        needs = dict(needs)
        found: dict = {}
        # 0) our own disk (no network)
        for shard in list(needs):
            local = self._stray_local(pg, oid, shard, acting, needs[shard])
            if local is not None:
                found[shard] = local
                del needs[shard]
        if not needs:
            return found

        def fetch_wave(targets: dict) -> dict:
            """{shard: osd} -> {shard: (data, ver, size)} that qualify;
            drops non-qualifying/err shards from nothing but the wave."""
            tids: dict = {}
            for shard, osd in targets.items():
                self._send_stray_read(pg, oid, shard, osd, False, tids,
                                      (shard, osd))
            reps = self._wait_replies(
                tids, time.monotonic() + self.FETCH_TIMEOUT
            )
            out = {}
            for tid, rep in reps.items():
                shard, osd = tids[tid]
                if rep is None or rep.retval != 0:
                    continue
                ver = getattr(rep, "ver", None)
                min_ver = needs.get(shard)
                if min_ver is not None and (ver is None or ver < min_ver):
                    continue
                out[shard] = (
                    unpack_data(rep.data),
                    ver,
                    int(rep.size) if rep.size is not None else None,
                )
                pg.stray_loc[shard] = osd
            return out

        # 1) cached locations: straight to a fetch wave
        cached = {
            sh: pg.stray_loc[sh] for sh in needs
            if sh in pg.stray_loc and self.osdmap.is_up(pg.stray_loc[sh])
        }
        if cached:
            hit = fetch_wave(cached)
            for shard in cached:
                if shard in hit:
                    found[shard] = hit[shard]
                    del needs[shard]
                else:
                    pg.stray_loc.pop(shard, None)  # stale cache entry
        if not needs:
            return found

        # 2) metadata wave: all candidates of all shards, one deadline.
        # Candidate order (reference: missing_loc built from
        # PastIntervals): past holders of THIS shard first — the only
        # OSDs that can plausibly hold it — then the bounded global walk
        # as a suffix so an INCOMPLETE history still finds a holder.
        probe_tids: dict = {}
        for shard, min_ver in needs.items():
            holder = acting[shard] if shard < len(acting) else -1
            exclude = {self.id, holder}
            candidates = pg.past_intervals.holders_of_shard(shard, exclude)
            seen = set(candidates)
            candidates += [
                osd for osd in range(self.osdmap.max_osd)
                if osd not in exclude and osd not in seen
            ]
            probes = 0
            for osd in candidates:
                if not self.osdmap.is_up(osd):
                    continue
                if probes >= self.PROBES_PER_SHARD:
                    break
                probes += 1
                self.logger.inc("stray_probes")
                self._send_stray_read(pg, oid, shard, osd, True,
                                      probe_tids, (shard, osd))
        reps = self._wait_replies(
            probe_tids, time.monotonic() + self.PROBE_TIMEOUT
        )
        # best holder per shard: highest NUMERIC generation wins (the
        # target only ever rises); wildcard stamps qualify only when
        # min_ver is None
        best: dict = {}
        for tid, rep in reps.items():
            shard, osd = probe_tids[tid]
            if shard in found or rep is None or rep.retval != 0:
                continue
            ver = getattr(rep, "ver", None)
            min_ver = needs.get(shard)
            if min_ver is not None and (ver is None or ver < min_ver):
                continue
            rank = -1 if ver is None else ver
            if shard not in best or rank > best[shard][0]:
                best[shard] = (rank, osd)

        # 3) fetch wave from the best holders
        if best:
            hit = fetch_wave({sh: osd for sh, (_r, osd) in best.items()})
            found.update(hit)
        return found

    # .. cephread: the read batcher's transport/store adapter ..............
    # (osd/read_batcher.py drives these from its flusher thread; bench
    # and tests substitute a local fake with the same surface)
    def rb_local_osd(self) -> int:
        return self.id

    def rb_is_up(self, osd: int) -> bool:
        return self.osdmap.is_up(osd)

    def rb_epoch(self) -> int:
        return self.my_epoch()

    def rb_reply_timeout(self) -> float:
        return float(self.cct.conf.get("osd_subop_reply_timeout"))

    def rb_read_local(self, pgid, shard: int, oid: str, off, ln):
        """Serve one batched descriptor from the local store: (bytes,
        ver, size) or None.  Full-chunk reads pass the same
        ``osd.ec.shard_read`` injection surface and hinfo CRC verify as
        `_gather_chunks`' local branch; ranged reads match
        `_fetch_shard_range`'s local branch (plain length-checked store
        read)."""
        cid = self._cid(pgid, shard)
        if off is not None:
            try:
                b = self.store.read(cid, oid, off, ln)
            except (NotFound, KeyError):
                return None
            if len(b) != ln:
                return None
            return bytes(b), self._stored_ver(cid, oid), None
        try:
            failpoint("osd.ec.shard_read", cct=self.cct,
                      entity=self.whoami, pgid=pgid, shard=shard, oid=oid)
            chunk = self.store.read(cid, oid)
        except FailpointCrash:
            raise
        except (FailpointError, NotFound, KeyError):
            return None
        try:
            stored = int(self.store.getattr(cid, oid, "hinfo"))
        except (NotFound, KeyError, ValueError):
            stored = None
        if stored is not None and crc32c(chunk) != stored:
            self.cct.dout(
                "osd", 0,
                f"{self.whoami} hinfo mismatch on local read "
                f"{pgid}/{oid} shard {shard}",
            )
            return None
        try:
            size = int(self.store.getattr(cid, oid, "size"))
        except (NotFound, KeyError):
            size = None
        return chunk, self._stored_ver(cid, oid), size

    def rb_send_multiread(self, osd: int, pgid, shard: int, reads,
                          epoch: int):
        tid = self._next_tid()
        try:
            self._conn_to_osd(osd).send_message(
                MECSubOpRead(tid=tid, pgid=pgid, oid=None, shard=shard,
                             offsets=None, epoch=epoch, reads=reads)
            )
        except (OSError, ConnectionError):
            return None
        return tid

    def rb_wait_multireads(self, tids, deadline: float) -> dict:
        return self._wait_replies(tids, deadline)

    def _rb_gather_data(self, pg, codec, acting, oid: str, want: set[int],
                        sizes: dict, vers: dict, size_hint):
        """Coalesced stand-in for `_gather_chunks` over acting data
        shards (no stray probing — degraded ops take the historical
        probe path).  Returns the got dict, or None when the batcher is
        absent/not coalescing/failed, in which case the caller falls
        back to the per-op fan-out."""
        rb = getattr(self, "read_batcher", None)
        if rb is None or not rb.coalescing():
            return None
        from .read_batcher import ReadReq

        reqs = [ReadReq(s, oid) for s in sorted(want)]
        est = len(reqs) * (codec.get_chunk_size(size_hint)
                           if size_hint else 4096)
        try:
            res = rb.gather(pg.pgid, acting, reqs, est_bytes=est)
        except Exception as e:
            self.cct.dout("osd", 1,
                          f"{self.whoami} batched gather failed, per-op "
                          f"fallback: {e!r}")
            return None
        got: dict[int, bytes] = {}
        for i, r in enumerate(reqs):
            row = res.get(i)
            if row is None or row[0] is None:
                continue
            got[r.shard] = row[0]
            vers[r.shard] = row[1]
            if row[2] is not None:
                sizes[r.shard] = int(row[2])
        return got

    # .. cephread: ranged degraded decode ..................................
    def _ranged_decode_ok(self, codec) -> bool:
        """Range-limited decode is exact only for plain byte-column-
        local MDS matrix codes with identity placement (the
        `_batch_matrix` property, decode-side): sub-chunked codecs
        (CLAY couples columns across sub-chunk planes) and non-jax
        referee backends keep the full decode + slice path."""
        if getattr(codec, "_jax_codec", None) is None:
            return False
        try:
            return bool(codec.supports_parity_delta()) \
                and codec.get_sub_chunk_count() == 1
        except (AttributeError, NotImplementedError):
            return False

    @staticmethod
    def _read_col_window(msg, k: int, L: int, size: int):
        """Column window (c0, c1) of every chunk that covers the
        requested byte range, or None when the request needs the full
        stripe.  Only a range that lands inside ONE data chunk gets a
        sub-window (a spanning range's column union is [0, L) anyway:
        the first chunk contributes a suffix, the next a prefix)."""
        if not (msg.off or (msg.length or 0) > 0):
            return None
        off = msg.off or 0
        end = min(off + msg.length, size) if msg.length else size
        if off >= end:
            return (0, 0)  # empty result: nothing to decode at all
        c_lo = off // L
        c_hi = (end - 1) // L
        if c_lo != c_hi or c_lo >= k:
            return None
        return (off % L, (end - 1) % L + 1)

    def _rb_decode_window(self, codec, use: dict, k: int,
                          c0: int, c1: int):
        """Decode ONLY columns [c0, c1) of each data chunk through the
        codec's cached decode matrix: {chunk id: [c1-c0] array}, or None
        if a full matrix can't be formed.  Rows are the first k
        available chunks in sorted order — the exact selection
        `RSCodec.decode_chunks` makes, so the windowed bytes are
        bit-identical to full-decode-then-slice.  The apply is fused
        with the flush's other decodes by the read batcher (pooled
        commit + one dispatch); bit-column locality makes the column
        slice exact."""
        rows = tuple(sorted(use))[:k]
        if len(rows) < k:
            return None
        jc = codec._jax_codec
        dm, dm_key = jc._decode_entry(rows)
        stack = np.stack([np.asarray(use[r], np.uint8)[c0:c1]
                          for r in rows])
        rb = getattr(self, "read_batcher", None)
        if rb is not None:
            out = rb.decode(dm, stack, dm_key)
        else:
            from ..ops.bitplane import apply_matrix_jax
            from ..ops.device_pool import POOL

            dev = POOL.put(stack) if POOL.enabled() else stack
            try:
                out = np.asarray(  # noqa: CL8 — decoded range serializes straight into the client reply
                    apply_matrix_jax(dm, dev, mat_key=dm_key),
                    dtype=np.uint8)
            finally:
                if dev is not stack:
                    POOL.release(dev)
        return {i: out[i] for i in range(k)}

    # .. cephread: hot-object cache plumbing ...............................
    def _read_cache_promote(self) -> bool:
        """cephmeter-driven promotion gate: cache a full-object read
        only when the requesting (client, pool) identity has accumulated
        `osd_read_cache_promote_ops` read ops in the per-client
        accounting table (threshold 0 = promote everything) — a heavy
        hitter's working set sticks, a cold scan never churns."""
        thresh = int(self.cct.conf.get("osd_read_cache_promote_ops"))
        if thresh <= 0:
            return True
        st = op_trace()
        acct = st.get("acct") if st is not None else None
        if acct is None:
            return False
        tab, client, pool = acct
        return tab.reads_of(client, pool) >= thresh

    def _ec_read(self, pg, codec, acting, msg) -> MOSDOpReply:
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        my_shard = acting.index(self.id) if self.id in acting else -1
        floor = pg.log.obj_newest.get(msg.oid)
        cache = getattr(self, "read_cache", None)
        if cache is not None and cache.enabled():
            hit = cache.get((pg.pgid, msg.oid), floor)
            if hit is not None:
                self.logger.inc("read_cache_hits")
                obj, size = hit
                if msg.off or (msg.length or 0) > 0:
                    off = msg.off or 0
                    ln = msg.length if msg.length else len(obj) - off
                    obj = obj[off:off + ln]
                return MOSDOpReply(tid=msg.tid, retval=0,
                                   epoch=self.my_epoch(),
                                   data=pack_data(obj),
                                   result={"size": size})
            self.logger.inc("read_cache_misses")
        # size from any shard we can reach (primary's own shard normally)
        size = None
        if my_shard >= 0:
            try:
                size = int(self.store.getattr(
                    self._cid(pg.pgid, my_shard), msg.oid, "size"))
            except (NotFound, KeyError):
                pass
        peer_sizes: dict[int, int] = {}
        vers: dict[int, int | None] = {}
        want_data = set(range(k))
        t_g0 = trace_now()
        got = self._rb_gather_data(pg, codec, acting, msg.oid, want_data,
                                   peer_sizes, vers, size)
        if got is None:
            got = self._gather_chunks(
                pg, codec, acting, msg.oid, want_data, sizes=peer_sizes,
                vers=vers, floor=floor,
            )
        self._op_stage("read_gather", t_g0, trace_now(), shards=len(got))

        got = _current_generation(got, vers, floor)
        missing = want_data - set(got)
        if missing:
            # degraded: consult minimum_to_decode over everything
            # reachable, including stray (non-acting) chunk locations
            avail_probe = self._gather_chunks(
                pg, codec, acting, msg.oid, set(range(k, n)) | missing,
                sizes=peer_sizes, vers=vers, stray=True, floor=floor,
            )
            avail_probe.update(got)
            avail_probe = _current_generation(avail_probe, vers, floor)
            if len(avail_probe) < k:
                return MOSDOpReply(
                    tid=msg.tid, retval=-5, epoch=self.my_epoch(),
                    result=f"unreadable: only {len(avail_probe)} chunks",
                )
            # zero-copy views over the gathered chunk bytes — the host
            # copies happen at the pooled decode seam below, not here
            chunks = {
                s: np.frombuffer(b, dtype=np.uint8)
                for s, b in avail_probe.items()
            }
            L = len(next(iter(chunks.values())))
            size = self._resolve_read_size(size, peer_sizes, vers, k * L)
            need = codec.minimum_to_decode(want_data, set(chunks))
            use = {s: chunks[s] for s in need if s in chunks}
            t_d0 = trace_now()
            win = self._read_col_window(msg, k, L, size) \
                if self._ranged_decode_ok(codec) else None
            if win is not None:
                # ranged fast path: decode ONLY the requested column
                # window through the cached decode matrix — the bytes
                # are identical to full-decode-then-slice, but the
                # kernel sees k x window instead of k x L bytes
                c0, c1 = win
                dec = self._rb_decode_window(codec, use, k, c0, c1) \
                    if c1 > c0 else {}
                if dec is not None:
                    self._op_stage("read_decode", t_d0, trace_now(),
                                   ranged=True, window=c1 - c0)
                    off = msg.off or 0
                    end = min(off + msg.length, size) if msg.length \
                        else size
                    obj = b"" if c1 <= c0 else \
                        np.asarray(dec[off // L], np.uint8)[
                            :end - off].tobytes()
                    return MOSDOpReply(tid=msg.tid, retval=0,
                                       epoch=self.my_epoch(),
                                       data=pack_data(obj),
                                       result={"size": size})
            dec = codec.decode(want_data, use, L)
            self._op_stage("read_decode", t_d0, trace_now(), ranged=False)
            data = b"".join(
                np.asarray(dec[i], np.uint8).tobytes() for i in range(k)
            )
        else:
            data = b"".join(got[i] for i in range(k))
        size = self._resolve_read_size(size, peer_sizes, vers, len(data))
        obj = data[:size]
        if cache is not None and cache.enabled() and not missing \
                and floor is not None and self._read_cache_promote():
            # healthy full-object read by a heavy hitter: cache the
            # assembled object at the PG log's newest version (the
            # validation stamp every later hit is checked against)
            cache.put((pg.pgid, msg.oid), floor, obj, size)
            self.logger.inc("read_cache_inserts")
        if msg.off or (msg.length or 0) > 0:
            off = msg.off or 0
            ln = msg.length if msg.length else len(obj) - off
            obj = obj[off : off + ln]
        return MOSDOpReply(tid=msg.tid, retval=0, epoch=self.my_epoch(),
                           data=pack_data(obj),
                           result={"size": size})

    @staticmethod
    def _resolve_read_size(size, peer_sizes: dict, vers: dict,
                           fallback: int) -> int:
        """Object size for padding-strip: the primary's own xattr if it
        had one, else a size reported by a current-generation shard (a
        stale shard's size xattr predates the newest RMW), else the
        full padded stripe length."""
        if size is not None:
            return size
        if peer_sizes:
            present = [v for v in vers.values() if v is not None]
            target = max(present) if present else None
            good = [
                sz for s, sz in peer_sizes.items()
                if target is None or vers.get(s) in (None, target)
            ]
            return good[0] if good else next(iter(peer_sizes.values()))
        return fallback

