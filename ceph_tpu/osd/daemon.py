"""OSD daemon — the EC data plane tied end-to-end (reference: src/osd/OSD.cc
boot/dispatch, src/osd/PrimaryLogPG.cc op execution, src/osd/ECBackend.cc
encode/fan-out/reconstruct/recover; SURVEY.md §3.1-3.2 call stacks).

One OSD process = messenger (lossless peer policy) + MonClient session +
ObjectStore + per-PG state.  The data model is the reference's at object
granularity:

- write: primary encodes the object through the pool's EC profile codec
  (ErasureCodePluginRegistry — the TPU path), ships one chunk per shard as
  MECSubOpWrite (each carrying the pg_log entry), commits its own shard,
  acks the client at >= min_size shard commits after an UPFRONT min_size
  reachability gate (ECBackend::submit_transaction shape + PrimaryLogPG's
  min_size refusal).
- ranged write / append: partial-stripe RMW as a parity-delta update —
  touched data shards get spliced segments, parity shards GF-XOR one
  matrix-apply's worth of delta over just the touched column window
  (reference: ECTransaction::generate_transactions, in the optimized-EC
  delta formulation).  Safety comes from per-object version stamps
  (object_info_t analog): stale-generation shards refuse the delta and
  are rebuilt by recovery; resends are answered by the per-PG reqid dup
  cache (pg_log dup entries analog).
- read: primary gathers k chunks (local + MECSubOpRead), reconstructs
  through minimum_to_decode/decode when shards are gone
  (objects_read_and_reconstruct), reassembles bytes.
- recovery: on map change the primary runs peering-lite — MPGQuery each
  acting shard, delta-push objects the peer's pg_log version misses
  (PGLog.missing_since), or full-backfill a shard whose log is too old
  (recover_object / backfill split, §5.4).

Scope notes vs the reference: scalar versions rather than eversion_t, and
peering without the boost::statechart machine — the invariants these
protect (log/data atomicity, min_size-gated acks, delta-vs-backfill
choice, no mixed-generation decodes, missing_loc-style stray-source
recovery) are kept.
"""
from __future__ import annotations


import threading
import time

from ..common.failpoint import failpoint, registry as fp_registry
from ..common.io_accounting import IOAccounting
from ..common.kernel_telemetry import (
    DEVICE_PERF,
    SENTINEL,
    TELEMETRY,
    SentinelPolicy,
)
from ..common.lockdep import make_lock
from ..common.perf_counters import PerfCountersBuilder
from ..common.recovery_accounting import RecoveryAccounting
from ..common.tracer import TRACER, op_trace, sampled_ctx, trace_now
from ..common.tracked_op import OpTracker
from ..ec.registry import ErasureCodePluginRegistry
from ..mon.mon_client import MonClient
from ..msg import Dispatcher, Messenger
from ..msg.messenger import POLICY_LOSSLESS_PEER
from ..osd.osdmap import OSDMap
from ..store.memstore import MemStore
from ..store.object_store import NotFound, Transaction
from .messages import (
    MECSubOpRead,
    MWatchNotifyAck,
    MECSubOpReadReply,
    MECSubOpWrite,
    MECSubOpWriteReply,
    MOSDOp,
    MOSDOpReply,
    MOSDPingMsg,
    MPGClean,
    MPGNotify,
    MPGPull,
    MPGPullReply,
    MPGQuery,
    MScrubShard,
    MScrubShardReply,
)
from ..mgr.messages import MQoSSettings
from .pg_log import LogEntry, PGLog
from .scheduler import MClockScheduler, QoSParams, SchedulerPerf
from .ec_backend import ECBackendMixin
from .object_ops import ObjectOpsMixin
from .pg import (  # noqa: F401  (re-exported: long-standing import surface)
    CLONE_SEP,
    MUTATING_OPS,
    PGState,
    _current_generation,
)
from .primary_ops import PrimaryOpsMixin
from .recovery import RecoveryMixin
from .replicated_backend import ReplicatedBackendMixin
from .scrub import ScrubMixin
from .read_batcher import ReadBatcher
from .read_cache import ReadCache
from .split_migration import SplitMigrationMixin
from .subops import SubOpsMixin
from .tiering import TieringMixin
from .write_batcher import WriteBatcher



class OSD(
    Dispatcher,
    PrimaryOpsMixin,
    ECBackendMixin,
    ObjectOpsMixin,
    ReplicatedBackendMixin,
    TieringMixin,
    SubOpsMixin,
    ScrubMixin,
    SplitMigrationMixin,
    RecoveryMixin,
):
    """reference: src/osd/OSD.{h,cc} (boot, dispatch, heartbeats) +
    PrimaryLogPG/ECBackend op execution, collapsed to one class."""

    def __init__(self, cct, osd_id: int, mon_addrs, store=None):
        self.cct = cct
        self.id = osd_id
        self.whoami = f"osd.{osd_id}"
        if store is not None:
            self.store = store
        else:
            # config-driven backend (reference: OSD reads `osd objectstore`)
            kind = cct.conf.get("objectstore")
            if kind == "memstore":
                self.store = MemStore()
            else:
                import os

                from ..store.object_store import create_store

                data_dir = cct.conf.get("osd_data") or None
                if data_dir:
                    # per-daemon subdir (reference: osd_data defaults to
                    # /var/lib/ceph/osd/$cluster-$id — never shared)
                    data_dir = os.path.join(data_dir, self.whoami)
                self.store = create_store(
                    kind,
                    data_dir,
                    compression=cct.conf.get("objectstore_compression"),
                    sync=cct.conf.get("objectstore_wal_sync"),
                    checksum=cct.conf.get("objectstore_checksum"),
                    device_size=cct.conf.get("bluestore_block_size"),
                )
                if cct.conf.get("osd_fsck_on_mount"):
                    # boot-time consistency pass over the freshly
                    # mounted (WAL-replayed) store (reference:
                    # bluestore_fsck_on_mount)
                    errs = self.store.fsck()
                    bad = (
                        errs.get("errors") if isinstance(errs, dict)
                        else errs
                    )
                    if bad:
                        raise RuntimeError(
                            f"{self.whoami} fsck on mount: {bad}"
                        )
        # tag the store with its owner so store-layer failpoints
        # (osd.store.write_before/after_commit) can match per-daemon —
        # both by entity name (thrasher-style entries) and by context
        # (config/admin-socket-scoped entries)
        self.store.fp_entity = self.whoami
        self.store.fp_cct = cct
        self.messenger = Messenger.create(cct, self.whoami)
        self.messenger.default_policy = POLICY_LOSSLESS_PEER
        self.messenger.add_dispatcher(self)
        # ticket validation tracks the map's auth generation, so `auth
        # rotate` cuts stale clients off as soon as this OSD sees the
        # new epoch (reference: rotating service keys via MAuth)
        self.messenger.auth_gen_provider = lambda: (
            self.osdmap.auth_gens.get("osd", 1) if self.osdmap else 1
        )
        self.mc = MonClient(cct, mon_addrs, name=f"{self.whoami}-monc")
        self.osdmap: OSDMap | None = None
        self.pgs: dict[str, PGState] = {}
        self._pgs_lock = make_lock("osd::pgs")
        self._lock = make_lock("osd::daemon")
        self._cond = threading.Condition(self._lock)
        self._sub_replies: dict[int, dict] = {}   # tid -> reply fields
        # cephstorm: freshest piggybacked load per peer OSD —
        # {osd id: (monotonic ts, mclock qlen, sentinel degraded)} from
        # sub-op reply telemetry; _plan_repair_read's cost-aware helper
        # choice reads it (stale entries past osd_repair_telemetry_ttl
        # are ignored, falling back to index order)
        self._peer_load: dict[int, tuple] = {}
        self._tid = 0
        self._stop = threading.Event()
        self._tick_thread: threading.Thread | None = None
        self._hb_failures: dict[int, int] = {}
        self._hb_reported: set[int] = set()  # peers we told the mon are down
        self._codecs: dict[str, object] = {}
        self._recovery_wakeup = threading.Event()
        # mClock QoS dispatch (reference: osd_mclock_profile
        # balanced-ish): client I/O keeps a reservation floor; recovery
        # and scrub share leftovers under ceilings.  cephqos grows the
        # client side into bounded DYNAMIC per-(client,pool) classes
        # (keyed by the cephmeter accounting identity) so the mgr's QoS
        # controller can retune individual tenants; the background
        # classes stay static and keep their floors (docs/qos.md)
        self._qos_classes = bool(cct.conf.get("osd_mclock_client_classes"))
        self.scheduler = MClockScheduler(
            {
                "client": QoSParams(reservation=100.0, weight=10.0),
                "background_recovery": QoSParams(
                    reservation=10.0, weight=2.0, limit=200.0
                ),
                "background_scrub": QoSParams(weight=1.0, limit=50.0),
            },
            max_dynamic=(
                int(cct.conf.get("osd_mclock_max_client_classes"))
                if self._qos_classes else 0
            ),
            # per-client default mirrors the static client class, so
            # flipping dynamic classes on changes attribution, not QoS
            dynamic_params=QoSParams(reservation=100.0, weight=10.0),
            # bounded client-op execution (reference: osd_op_tp's fixed
            # thread count): while all slots are busy, dynamic classes
            # are ineligible to dequeue, so mClock's tags decide who
            # runs NEXT — an unbounded pool would drain the queue
            # instantly and the tags would order nothing.  Internal
            # OSD-to-OSD forwards ride the exempt static "client"
            # class (deadlock-free forwarding)
            client_slots=int(cct.conf.get("osd_mclock_client_slots")),
        )
        # monotonically increasing settings epoch: stale controller
        # pushes (reordered frames, a deposed mgr) must not roll QoS
        # back; flipped under self._lock
        self._qos_epoch = 0
        # per-class depth/served/wait as labeled prometheus series
        # (perf dump -> MMgrReport -> prometheus; docs/qos.md)
        cct.perf.add(SchedulerPerf(self.scheduler))
        self._workers: list[threading.Thread] = []
        # op-thread watchdog (reference: HeartbeatMap / osd_op_thread_
        # timeout): _run_op stamps ident -> [name, class, start,
        # last_warn]; the tick loop complains about entries older than
        # the grace.  Keyed by thread ident, not name — concurrent
        # client ops share the "-op" thread name
        self._worker_busy: dict[int, list] = {}
        self._worker_busy_lock = make_lock("osd::op_watchdog")
        self._recovery_inflight = False
        self._split_inflight = False
        self._sentinel_held = False  # flipped under self._lock
        self._pool_observer = None  # conf observer, deregistered at stop
        self.device_policy = None  # injected at start() (cephtopo)
        self._clone_mutex = make_lock("osd::snap_clone")
        # watch/notify state (reference: PrimaryLogPG watchers): primary-
        # local; clients re-register lingering watches on map change
        self.watchers: dict[tuple, dict[int, str]] = {}
        self._watch_lock = make_lock("osd::watch")
        self._client_conns: dict[str, object] = {}
        self._watch_cond = threading.Condition()
        self._notify_acks: dict[tuple[int, int], bool] = {}
        self._last_scrub = 0.0
        self._scrubs_queued: set[str] = set()
        # reference: OSD::create_logger (l_osd_op / l_osd_op_w / ...)
        self.logger = cct.perf.add(
            PerfCountersBuilder("osd")
            .add_u64_counter("op", "client operations")
            .add_u64_counter("op_w", "client writes")
            .add_u64_counter("op_r", "client reads")
            .add_u64_counter("op_w_bytes", "bytes written")
            .add_u64_counter("op_r_bytes", "bytes read")
            .add_time_avg("op_latency", "op latency")
            .add_u64_counter("recovery_ops", "objects pushed in recovery")
            .add_u64_counter("stray_probes", "stray-location probes sent")
            .add_u64_counter("subop_w", "shard sub-writes applied")
            .add_u64_counter("scrubs", "PG scrubs completed")
            .add_u64_counter("scrub_errors", "shard inconsistencies found")
            .add_u64_counter("scrub_repairs", "shards repaired by scrub")
            .add_u64_counter("tier_promote", "cache-tier promotions")
            .add_u64_counter("tier_flush", "cache-tier flushes")
            .add_u64_counter("tier_evict", "cache-tier evictions")
            .add_u64_counter("ec_batch_flushes",
                             "coalesced encode batches flushed")
            .add_u64_counter("ec_batch_stripes",
                             "stripes encoded through the write batcher")
            .add_u64_counter("ec_batch_bytes",
                             "data bytes encoded through the write batcher")
            .add_u64_counter("ec_batch_inline",
                             "stripes encoded inline (coalescing off)")
            .add_time_avg("ec_batch_flush_latency",
                          "coalesced flush latency")
            # cephread: the coalesced READ plane (osd/read_batcher.py)
            # and the primary's hot-object cache (osd/read_cache.py);
            # rides the same perf dump -> MMgrReport -> prometheus
            # pipeline as the write-batcher series
            .add_u64_counter("read_batcher_flushes",
                             "coalesced read batches flushed")
            .add_u64_counter("read_batcher_ops",
                             "gather/decode ops through the read batcher")
            .add_u64_counter("read_batcher_bytes",
                             "bytes gathered/decoded through the read "
                             "batcher")
            .add_u64_counter("read_batcher_inline",
                             "read ops served inline (coalescing off)")
            .add_time_avg("read_batcher_flush_latency",
                          "coalesced read-flush latency")
            .add_u64_counter("read_cache_hits",
                             "hot-object cache hits")
            .add_u64_counter("read_cache_misses",
                             "hot-object cache misses")
            .add_u64_counter("read_cache_inserts",
                             "objects promoted into the read cache")
            .add_u64_counter("read_cache_evictions",
                             "read-cache LRU evictions (byte bound)")
            .add_u64_counter("read_cache_invalidations",
                             "read-cache entries dropped by write-path "
                             "version bumps")
            # per-stage latency histograms (cephtrace aggregation;
            # log2 buckets, reference: PerfHistogram).  Names match the
            # span taxonomy in common/tracer.py OP_STAGES exactly.
            .add_time_histogram("stage_admission",
                                "write-batcher admission-throttle wait")
            .add_time_histogram("stage_queue",
                                "stripe coalescing wait (queued to "
                                "flush start)")
            .add_time_histogram("stage_encode",
                                "fused device encode per flush")
            .add_time_histogram("stage_subop",
                                "sub-op fan-out to last shard ack")
            .add_time_histogram("stage_commit",
                                "local object-store commit")
            # cephread client-plane stages (the PR-9 trace tree grows
            # read-side spans): gather = chunk fan-out wall time,
            # decode = degraded reconstruct (ranged or full)
            .add_time_histogram("stage_read_gather",
                                "read chunk gather (batched fan-out or "
                                "per-op)")
            .add_time_histogram("stage_read_decode",
                                "degraded-read decode (ranged window "
                                "or full stripe)")
            # background-plane stage histograms (cephheal): names match
            # tracer.BG_STAGES / the recovery and scrub span taxonomy
            # verbatim, like stage_* matches OP_STAGES
            .add_time_histogram("recovery_peer",
                                "recovery peer-query round (MPGQuery "
                                "versions + object lists)")
            .add_time_histogram("recovery_pull",
                                "authoritative-log catch-up wait "
                                "(MPGPull to donor reply)")
            .add_time_histogram("recovery_rebuild",
                                "one shard chunk rebuilt (helper "
                                "gather + decode)")
            .add_time_histogram("recovery_push",
                                "one peer's push round (delta replay "
                                "or backfill)")
            .add_time_histogram("scrub_read",
                                "shard ScrubMap collection")
            .add_time_histogram("scrub_compare",
                                "cross-shard digest comparison")
            .add_time_histogram("scrub_repair",
                                "flagged-shard rebuild + re-push")
            .add_u64_counter("recovery_errors",
                             "per-PG recovery passes that raised "
                             "(previously a dout-level-1 line only)")
            .add_u64("numpg", "placement groups hosted")
            .create_perf_counters()
        )
        # cephheal: per-(pool,codec) repair-bandwidth table — helper
        # shards/bytes read vs bytes repaired, the live CLAY-vs-RS
        # repair ratio (common/recovery_accounting.py); duck-types
        # PerfCounters so the labeled rows ride perf dump ->
        # MMgrReport -> prometheus as ceph_recovery_*{pool,codec}
        self.recovery_acct = cct.perf.add(RecoveryAccounting())
        # consecutive _recover_pg failures per PG (satellite: a PG
        # failing every tick must surface in RECOVERY_STALLED, not
        # scroll away in logs); pgid -> [count, last_error], under
        # self._lock (recovery worker writes, report tick reads)
        self._recovery_failures: dict[str, list] = {}
        # the process-wide kernel telemetry registry rides this daemon's
        # perf pipeline (perf dump -> MMgrReport -> prometheus): kernels
        # are per-process, so every OSD in a LocalCluster reports the
        # same shared "kernel" subsystem (docs/observability.md)
        if cct.perf.get(TELEMETRY.perf.name) is None:
            cct.perf.add(TELEMETRY.perf)
        # cephplace satellite: the sentinel's per-device probe rows ride
        # the same pipeline as ceph_backend_device_*{device} labeled
        # series (one row per jax device, verdict + probe latency)
        if cct.perf.get(DEVICE_PERF.name) is None:
            cct.perf.add(DEVICE_PERF)
        # coalescing encode layer in front of the GF codec (the batched
        # write path; osd/write_batcher.py, docs/write_path.md)
        self.write_batcher = WriteBatcher(cct, logger=self.logger,
                                          entity=self.whoami)
        # cephread: the coalescing gather/decode layer behind _ec_read
        # (osd/read_batcher.py; this OSD is its transport adapter via
        # ECBackendMixin's rb_* methods) plus the primary's hot-object
        # cache (osd/read_cache.py, byte-bounded, runtime-resizable)
        self.read_batcher = ReadBatcher(cct, io=self, logger=self.logger,
                                        entity=self.whoami)
        self.read_cache = ReadCache(
            int(cct.conf.get("osd_read_cache_bytes")), logger=self.logger)
        cct.conf.add_observer(
            ["osd_read_cache_bytes"],
            lambda _n, v: self.read_cache.set_max_bytes(int(v)))
        # in-flight + historic op tracking (reference: OSD's OpTracker;
        # src/common/TrackedOp.cc — serves dump_ops_in_flight /
        # dump_historic_ops on the admin socket and feeds the SLOW_OPS
        # health check through the mgr digest)
        self.op_tracker = OpTracker(
            history_size=int(cct.conf.get("osd_op_history_size")),
            complaint_time=float(cct.conf.get("osd_op_complaint_time")),
            recent_slow_window=float(cct.conf.get("osd_slow_op_window")),
        )
        # cephmeter: per-(client,pool) accounting — the labels are the
        # future mClock QoS tags (common/io_accounting.py).  The table
        # duck-types PerfCounters, so adding it to cct.perf makes the
        # labeled series ride perf dump -> MMgrReport -> prometheus
        # with zero new wire plumbing (docs/observability.md)
        self.io_acct: IOAccounting | None = None
        if cct.conf.get("osd_client_io_accounting"):
            self.io_acct = IOAccounting(
                "client_io",
                top_k=int(cct.conf.get("osd_client_io_top_k")),
            )
            cct.perf.add(self.io_acct)
        if cct.admin_socket is not None:
            cct.admin_socket.register_command(
                "dump_ops_in_flight",
                lambda c: self.op_tracker.dump_ops_in_flight(),
                "ops currently executing",
            )
            cct.admin_socket.register_command(
                "dump_historic_ops",
                lambda c: self.op_tracker.dump_historic_ops(),
                "recently completed ops",
            )
            cct.admin_socket.register_command(
                "dump_historic_bg_ops",
                lambda c: self.op_tracker.dump_historic_bg_ops(),
                "recently completed background (recovery/scrub) ops "
                "with per-stage attribution (cephheal)",
            )
            cct.admin_socket.register_command(
                "dump_historic_slow_ops",
                lambda c: self.op_tracker.dump_historic_slow_ops(),
                "completed slow ops with per-stage attribution and "
                "(when cephtrace kept or tail-promoted the trace) the "
                "assembled cross-entity trace tree",
            )
            cct.admin_socket.register_command(
                "dump_op_queue",
                lambda c: self.scheduler.dump(),
                "mClock per-class queue depth, served ops, wait "
                "histograms, and (reservation, weight, limit) params "
                "(docs/qos.md)",
            )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.store.mount()
        addr = self.messenger.bind(("127.0.0.1", 0))
        self.messenger.start()
        self.mc.subscribe_osdmap(callback=self._on_map)
        self.mc.fetch_config(self.cct)  # central config (mon db)
        # resend boot until the map shows our address (reference: OSD
        # re-sends MOSDBoot until it sees itself up) — a boot riding a
        # connection that resets mid-handshake would otherwise be lost
        deadline = time.monotonic() + 30.0
        min_epoch = 1
        while True:
            try:
                self.mc.send_boot(self.id, addr)
            except (OSError, ConnectionError):
                pass
            try:
                m = self.mc.wait_for_osdmap(min_epoch=min_epoch, timeout=2.0)
            except TimeoutError:
                m = self.mc.osdmap
            if m is not None:
                if tuple(m.osd_addrs.get(self.id) or ()) == tuple(addr):
                    self.osdmap = m
                    break
                # wait for a NEWER epoch next round so the retry loop
                # blocks instead of spinning on the same stale map
                min_epoch = m.epoch + 1
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"{self.whoami}: boot not acknowledged in 30s"
                )
        self._load_pgs()
        # cephdma: device stripe pool sized/armed from THIS daemon's
        # conf (process-wide like the sentinel — first daemon at boot
        # wins the bound; the batcher re-reads ec_device_pool per flush
        # so the hatch stays runtime there, and an EXPLICIT injectargs
        # flips the process-wide pool too via the observer — that's
        # what lets the hatch disengage the stream/decode/recovery
        # paths, which consult only POOL.enabled())
        from ..ops.device_pool import POOL, configure_from_conf

        # cephtopo: device-topology policy from THIS daemon's conf
        # (device_topology / device_mesh_shape, read ONCE here),
        # constructor-injected process-wide — first daemon wins, like
        # the sentinel.  The mesh/pool/dispatch/CRUSH seams consult the
        # policy instead of ambient jax.devices() (cephlint CL9).
        from ..common.device_policy import (DevicePolicy,
                                            configure_device_policy)

        self.device_policy = configure_device_policy(
            DevicePolicy.from_conf(self.cct.conf))
        configure_from_conf(self.cct.conf, policy=self.device_policy)
        # keep the callback so shutdown can deregister it — a stopped
        # OSD reacting to a later injectargs would flip the
        # process-wide pool on behalf of a corpse
        self._pool_observer = lambda _n, v: POOL.configure(
            enabled=bool(v))
        self.cct.conf.add_observer(["ec_device_pool"],
                                   self._pool_observer)
        self.write_batcher.start()
        self.read_batcher.start()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"{self.whoami}-tick", daemon=True
        )
        self._tick_thread.start()
        # op worker pool draining the mClock queue (reference: osd_op_tp)
        for i in range(2):
            t = threading.Thread(
                target=self._op_worker, name=f"{self.whoami}-op-{i}",
                daemon=True,
            )
            self._workers.append(t)
            t.start()
        # backend health sentinel (common/kernel_telemetry.py): policy
        # built from THIS daemon's conf and constructor-injected — the
        # sentinel itself is process-wide (kernel dispatch is), refs
        # counted across the local daemons; interval <= 0 disables.
        # Brought up LAST: a later bring-up failure escaping start()
        # would strand the refcount no later daemon can retire
        si = float(self.cct.conf.get("backend_sentinel_interval"))
        if si > 0:
            SENTINEL.acquire(SentinelPolicy(
                interval=si,
                timeout=float(self.cct.conf.get("backend_sentinel_timeout")),
            ))
            with self._lock:
                self._sentinel_held = True

    def _op_worker(self) -> None:
        while not self._stop.is_set():
            picked = self.scheduler.dequeue(timeout=1.0)
            if picked is None:
                continue
            cls, work = picked
            if cls in ("background_recovery", "background_scrub"):
                # background work runs inline: worker count bounds its
                # concurrency, which is the point of the QoS classes
                self._run_op(work, cls)
            else:
                # client-side classes ("client", per-client dynamic,
                # "_default_"): mClock orders ADMISSION; execution gets
                # its own thread so a client op blocked on a slow
                # peer's sub-op never pins a worker that background
                # work (or the recovery that would fix the peer) needs.
                # Dynamic-class ops consumed a client-op slot at the
                # pick (the bound that makes the tags bite); the
                # executor returns it via client_op_done()
                threading.Thread(  # noqa: CL13 — fire-and-forget by design: per-op executor; its lifetime is the op's, and the scheduler's inflight slot (returned via client_op_done) bounds the population
                    target=self._run_client_op,
                    args=(work, cls, cls != "client"),
                    name=f"{self.whoami}-op", daemon=True,
                ).start()

    def _run_client_op(self, work, cls: str, slotted: bool) -> None:
        try:
            self._run_op(work, cls)
        finally:
            if slotted and self.scheduler.client_slots > 0:
                self.scheduler.client_op_done()

    def _run_op(self, work, cls: str = "client") -> None:
        th = threading.current_thread()
        now = time.monotonic()
        with self._worker_busy_lock:
            self._worker_busy[th.ident] = [th.name, cls, now, now]
        try:
            work()
        except Exception as e:
            self.cct.dout("osd", 0, f"{self.whoami} op failed: {e!r}")
        finally:
            with self._worker_busy_lock:
                self._worker_busy.pop(th.ident, None)

    def _check_op_workers(self, now: float) -> None:
        """Complain about workers stuck past osd_op_thread_timeout
        (reference: HeartbeatMap::is_healthy's 'had timed out' log)."""
        grace = float(self.cct.conf.get("osd_op_thread_timeout"))
        with self._worker_busy_lock:
            entries = [e for e in self._worker_busy.values()
                       if now - e[2] >= grace and now - e[3] >= grace]
            for e in entries:
                e[3] = now
        for tname, cls, start, _ in entries:
            self.cct.dout(
                "osd", 0,
                f"{self.whoami} worker {tname} ({cls}) stuck for "
                f"{now - start:.1f}s (osd_op_thread_timeout {grace:.0f}s)")

    def shutdown(self, umount: bool = True) -> None:
        """umount=False is the thrasher's CRASH kill: threads stop but
        the store is dropped without a graceful unmount, so a revive
        from the same directory exercises real WAL replay + fsck."""
        self._stop.set()
        try:
            self.scheduler.stop()
        except Exception as e:
            self.cct.dout("osd", 0,
                          f"{self.whoami} scheduler stop raised: {e!r}")
        self._recovery_wakeup.set()
        # wake every blocked sub-op wait (_wait_reply/_wait_replies are
        # stop-aware) so the worker joins below don't sit out the
        # osd_subop_reply_timeout of an in-flight recovery pull
        with self._lock:
            self._cond.notify_all()
        # teardown reverses bring-up, each step best-effort (one bad
        # subsystem must not strand the rest, mgr/daemon.py style):
        # the sentinel ref first (bring-up's last step), then op
        # workers and the tick thread (they submit through everything
        # below), the coalescers (queued stripes flush — their ops
        # complete or fail normally — before the messenger goes away),
        # the conf observer, the transports, and last the store.
        # Test-and-set under the daemon lock (double-shutdown must not
        # double-release the refcounted sentinel)
        with self._lock:
            release_sentinel = self._sentinel_held
            self._sentinel_held = False
        if release_sentinel:
            try:
                SENTINEL.release()
            except Exception as e:
                self.cct.dout(
                    "osd", 0,
                    f"{self.whoami} sentinel release raised: {e!r}")
        for t in self._workers:
            t.join(timeout=5)
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        try:
            self.read_batcher.stop()
        except Exception as e:
            self.cct.dout("osd", 0,
                          f"{self.whoami} read batcher stop raised: "
                          f"{e!r}")
        try:
            self.write_batcher.stop()
        except Exception as e:
            self.cct.dout("osd", 0,
                          f"{self.whoami} write batcher stop raised: "
                          f"{e!r}")
        if self._pool_observer is not None:
            try:
                self.cct.conf.remove_observer(self._pool_observer)
            except Exception as e:
                self.cct.dout(
                    "osd", 0,
                    f"{self.whoami} observer removal raised: {e!r}")
            self._pool_observer = None
        try:
            self.mc.shutdown()
        except Exception as e:
            self.cct.dout("osd", 0,
                          f"{self.whoami} mon client shutdown raised: "
                          f"{e!r}")
        try:
            self.messenger.shutdown()
        except Exception as e:
            self.cct.dout("osd", 0,
                          f"{self.whoami} messenger shutdown raised: "
                          f"{e!r}")
        if umount:
            try:
                self.store.umount()
            except Exception as e:
                self.cct.dout("osd", 0,
                              f"{self.whoami} store umount raised: {e!r}")
        # the context goes last: its admin socket serves debug commands
        # (perf dump, failpoints) right up until the daemon is gone
        self.cct.shutdown()

    # -- map handling ------------------------------------------------------
    def _on_map(self, m: OSDMap) -> None:
        old = self.osdmap
        self.osdmap = m
        if old is not None:
            # interval bookkeeping (same_interval_since): a PG whose
            # up/acting changed starts a NEW interval at this epoch
            with self._pgs_lock:
                pgs = list(self.pgs.values())
            for pg in pgs:
                try:
                    o = old.pg_to_up_acting_osds(pg.pool_id, pg.ps)
                    n = m.pg_to_up_acting_osds(pg.pool_id, pg.ps)
                except Exception as e:
                    # pool deleted between the two epochs (or a map too
                    # old to place against) — the PG is on its way out
                    self.cct.dout("osd", 10,
                                  f"{self.whoami} interval check skipped "
                                  f"pg {pg.pool_id}.{pg.ps:x}: {e!r}")
                    continue
                if (o[2], o[3]) != (n[2], n[3]):
                    # close the old interval into the history BEFORE
                    # starting the new one (reference: check_new_interval)
                    old_pool = old.pools.get(pg.pool_id)
                    went_rw = (
                        o[3] >= 0
                        and old_pool is not None
                        and sum(1 for a in o[2] if a >= 0)
                        >= old_pool.min_size
                    )
                    # under pg.lock: recovery's clean-broadcast block
                    # clears past_intervals under the same lock, and an
                    # unserialized interleave here could close an
                    # interval into a history recovery just wiped
                    with pg.lock:
                        pg.past_intervals.add(
                            first=pg.interval_start or old.epoch,
                            last=m.epoch - 1,
                            up=o[0], acting=o[2], primary=o[3],
                            maybe_went_rw=went_rw,
                        )
                        pg.intervals_closed += 1
                        pg.interval_start = m.epoch
                    self._save_intervals(pg)
        if (old is None or old.max_pool_id != m.max_pool_id
                or set(old.pools) - set(m.pools)):
            self._purge_deleted_pools(m)
        self._recovery_wakeup.set()  # re-peer with the new map

    def _purge_deleted_pools(self, m: OSDMap) -> None:
        """Local PG state for any pool absent from the map is garbage
        (reference: the OSD's PG removal queue after pool deletion).
        Checked against the full map, not an old->new diff, so an OSD
        that was down across the deletion still purges on its first map
        after boot — _load_pgs resurrects PGs from leftover collections.
        Pool ids are monotonic (OSDMap.max_pool_id), which makes the
        check race-free against map lag: a collection whose pool id is
        ABOVE this map's max_pool_id belongs to a pool created in an
        epoch we haven't applied yet (a lagging replica can take a
        sub-op for it before seeing the map) and must be left alone;
        one at or below it that is absent from the map is definitively
        deleted, because ids are never reused."""

        def _pool_of(key: str) -> int:
            head = key.split(".", 1)[0]
            return int(head) if head.isdigit() else -1

        live = set(m.pools)
        ceiling = m.max_pool_id

        def _doomed(pid: int) -> bool:
            return 0 <= pid <= ceiling and pid not in live

        with self._pgs_lock:
            doomed = [k for k in self.pgs if _doomed(_pool_of(k))]
            for key in doomed:
                del self.pgs[key]
        for cid in list(self.store.list_collections()):
            pid = _pool_of(cid)
            if not _doomed(pid):
                continue
            try:
                t = Transaction()
                for oid in list(self.store.list_objects(cid)):
                    t.remove(cid, oid)
                t.remove_collection(cid)
                self.store.queue_transaction(t)
            except Exception as e:
                self.cct.dout(
                    "osd", 3,
                    f"{self.whoami} pool {pid} purge {cid}: {e!r}")

    def my_epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- helpers -----------------------------------------------------------
    def _codec_for_pool(self, pool):
        """Per-profile compiled codec cache (reference: ECBackend holds its
        ErasureCodeInterfaceRef; SURVEY.md §2.9 'per-profile kernel cache')."""
        name = pool.ec_profile or ""
        codec = self._codecs.get(name)
        if codec is None:
            profile = dict(self.osdmap.ec_profiles.get(name) or {})
            profile.setdefault("plugin", "jax")
            # ec_kernel: 'oracle'/'numpy' swap the whole backend for the
            # default plugin; 'xla'/'pallas' pick the GF kernel inside
            # the jax backend (process-wide, mirrors CEPH_TPU_EC_KERNEL)
            kern = str(self.cct.conf.get("ec_kernel"))
            if kern in ("oracle", "numpy") and profile["plugin"] == "jax":
                profile["plugin"] = kern
            elif kern in ("xla", "pallas"):
                from ..ops.bitplane import set_kernel_override
                set_kernel_override(kern)
            codec = ErasureCodePluginRegistry.instance().factory(profile)
            self._codecs[name] = codec
        return codec

    def _acting(self, pool_id: int, ps: int) -> tuple[list[int], int]:
        up, up_p, acting, acting_p = self.osdmap.pg_to_up_acting_osds(
            pool_id, ps
        )
        return acting, acting_p

    def _pg(self, pool_id: int, ps: int) -> PGState:
        pgid = f"{pool_id}.{ps}"
        with self._pgs_lock:
            pg = self.pgs.get(pgid)
            if pg is None:
                pg = PGState(pgid, pool_id, ps)
                self._load_pg_meta(pg)
                # an OSD (re)booting IS an interval change for its PGs:
                # without this a revived OSD would accept sub-ops from a
                # primary deposed while it was down (interval_start=0
                # would pass everything)
                pg.interval_start = self.my_epoch()
                self.pgs[pgid] = pg
            return pg

    def _cid(self, pgid: str, shard: int) -> str:
        return f"{pgid}s{shard}"

    def _conn_to_osd(self, osd: int):
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        conn = self.messenger.connect(tuple(addr))
        if not conn.peer_name:
            # dialer-side identity: lets send-path failpoints match on
            # the peer before any reply has arrived
            conn.peer_name = f"osd.{osd}"
        return conn

    def _next_tid(self) -> int:
        with self._lock:
            self._tid += 1
            return self._tid

    # -- cephtrace op-stage funnel -----------------------------------------
    def _op_stage(self, stage: str, t0: float, t1: float, span=None,
                  **tags) -> None:
        """ONE helper for op-stage bookkeeping: the stage histogram,
        the TrackedOp event (dump_historic_ops offsets), and the
        cephtrace span all share one clock (tracer.trace_now) and one
        stage name — they cannot drift apart (the double-booked-
        timestamp bug this replaces).  Stage names: tracer.OP_STAGES.
        `span` closes a pre-opened span (the subop fan-out opens its
        span BEFORE sending so sub-op messages can carry its id as
        their parent) instead of minting a fresh one."""
        self._stage_funnel(f"stage_{stage}", stage, t0, t1, span, tags)

    def _stage_funnel(self, counter: str, stage: str, t0: float,
                      t1: float, span, tags: dict) -> None:
        """The shared histogram + TrackedOp + span funnel behind
        _op_stage (client plane, `stage_*` counters) and _bg_stage
        (background plane, bare BG_STAGES counters)."""
        self.logger.hinc(counter, t1 - t0)
        st = op_trace()
        if st is None:
            TRACER.end(span, t1=t1, **tags)
            return
        tracked = st.get("tracked")
        if tracked is not None:
            tracked.mark_event(stage, ts=t1)
            # cephmeter: accumulated per-stage duration, so a slow op's
            # dump_historic_slow_ops entry names the dominant stage
            tracked.stage_add(stage, t1 - t0)
        if span is not None:
            TRACER.end(span, t1=t1, **tags)
            return
        ctx = st.get("ctx")
        if ctx is not None:
            TRACER.record(ctx, stage, entity=self.whoami, t0=t0, t1=t1,
                          **tags)

    def _op_trace_ctx(self):
        """Current op's trace context (None = unsampled / tracing off)."""
        st = op_trace()
        return st.get("ctx") if st is not None else None

    # -- cephheal background-op funnel ---------------------------------
    def _bg_stage(self, stage: str, t0: float, t1: float, span=None,
                  **tags) -> None:
        """_op_stage's background twin: one call feeds the recovery_*/
        scrub_* latency histogram, the TrackedOp stage attribution, and
        the cephtrace span — one clock, one stage name (tracer.
        BG_STAGES, which IS the counter name).  The histogram fills
        whether or not tracing is on; the span side is the usual
        one-attribute-check no-op when off."""
        self._stage_funnel(stage, stage, t0, t1, span, tags)

    def _bg_trace_ctx(self):
        """Root context for a background op (recovery pass, scrub):
        the SAME head-coin-flip + tail-provisional contract client ops
        get at op_submit, so a slow recovery keeps its connected tree
        even at trace_sampling_rate=0 (docs/tracing.md)."""
        if not TRACER.enabled:
            return None
        return sampled_ctx(
            float(self.cct.conf.get("trace_sampling_rate")),
            tail=float(self.cct.conf.get("trace_tail_latency_ms")) > 0,
        )

    def _bg_tail_verdict(self, tracked) -> None:
        """Promote-or-discard a background op's provisionally buffered
        trace on completion (the client-side Objecter verdict has no
        analog here — the background op IS its own client)."""
        tid = tracked.trace_id
        if tid is None:
            return
        dur = tracked.duration()
        complaint = self.op_tracker.complaint_time
        tail_ms = float(self.cct.conf.get("trace_tail_latency_ms"))
        if complaint > 0 and dur > complaint:
            TRACER.promote(tid, reason=f"{tracked.src}_complaint")
        elif tail_ms > 0 and dur * 1e3 >= tail_ms:
            TRACER.promote(tid, reason=f"{tracked.src}_tail")
        elif TRACER.is_provisional(tid):
            TRACER.discard(tid)

    def _codec_label(self, pool) -> str:
        """(pool, codec) label for the repair-bandwidth rows: the EC
        profile's plugin (+technique when set), or 'replica'."""
        from ..osd.osdmap import PG_POOL_ERASURE

        if pool is None:
            return "?"
        if pool.type != PG_POOL_ERASURE:
            return "replica"
        prof = ((self.osdmap.ec_profiles if self.osdmap else {})
                .get(pool.ec_profile or "") or {})
        plugin = str(prof.get("plugin", "jax"))
        tech = prof.get("technique")
        return f"{plugin}-{tech}" if tech else plugin

    # -- persistence of PG meta -------------------------------------------
    def _load_pgs(self) -> None:
        for cid in self.store.list_collections():
            if "s" not in cid or "." not in cid:
                continue
            pgid = cid.rsplit("s", 1)[0]
            pool_id, ps = pgid.split(".")
            self._pg(int(pool_id), int(ps))

    def _load_pg_meta(self, pg: PGState) -> None:
        from .past_intervals import PastIntervals

        # any shard collection of this pg carries the meta object
        for cid in self.store.list_collections():
            if cid.rsplit("s", 1)[0] != pg.pgid:
                continue
            try:
                pairs = self.store.omap_get(cid, pg.meta_oid())
            except (NotFound, KeyError):
                continue
            head = int(pairs.get("head", b"0"))
            tail = int(pairs.get("tail", b"0"))
            pg.log = PGLog.load(pairs, head, tail)
            pg.version = head
            pg.past_intervals = PastIntervals.from_bytes(
                pairs.get("past_intervals")
            )
            pg.last_map_epoch = int(pairs.get("last_epoch", b"0"))
            pg.meta_cids.add(cid)
            return

    def _save_intervals(self, pg: PGState) -> None:
        """Persist the interval history + rebuild floor next to the PG
        log (same meta omap; reference: PastIntervals + history ride
        pg_info_t in the pg meta).  Uses the PG's known shard
        collections (meta_cids) — a full store scan per map change was
        O(pgs x collections) on the map-handling path (review r4); the
        scan runs once, only when the cache is cold."""
        if not pg.meta_cids:
            pg.meta_cids = {
                cid for cid in self.store.list_collections()
                if cid.rsplit("s", 1)[0] == pg.pgid
            }
            if not pg.meta_cids:
                # no local collection yet (freshly assigned primary):
                # stash under the would-be-primary shard so the history
                # survives a restart
                pg.meta_cids = {self._cid(pg.pgid, 0)}
        # snapshot the two fields under pg.lock: the map thread and
        # recovery's clean-broadcast both mutate them under that lock,
        # and serializing the WRITERS is worthless if this reader can
        # still persist half of one writer's update.  The store txn
        # below stays outside the lock.
        with pg.lock:
            keys = {
                "past_intervals": pg.past_intervals.to_bytes(),
                "last_epoch": str(pg.last_map_epoch).encode(),
            }
        for cid in pg.meta_cids:
            t = Transaction()
            t.try_create_collection(cid)
            t.touch(cid, pg.meta_oid())
            t.omap_setkeys(cid, pg.meta_oid(), keys)
            self.store.queue_transaction(t)

    def _log_txn(self, t: Transaction, cid: str, pg: PGState,
                 entry: LogEntry) -> None:
        """Append the log entry + version keys to the same transaction as
        the data op (log/data atomicity, reference: PGLog::write_log)."""
        import json

        trimmed = pg.log.append(entry)
        pg.version = entry.version
        pg.last_map_epoch = self.my_epoch()
        keys = {
            PGLog.omap_key(entry.version): json.dumps(entry.to_list()).encode(),
            "head": str(pg.log.head).encode(),
            "tail": str(pg.log.tail).encode(),
            "last_epoch": str(pg.last_map_epoch).encode(),
        }
        t.touch(cid, pg.meta_oid())
        t.omap_setkeys(cid, pg.meta_oid(), keys)
        pg.meta_cids.add(cid)
        if trimmed:
            t.omap_rmkeys(
                cid, pg.meta_oid(), [PGLog.omap_key(e.version) for e in trimmed]
            )

    def _log_seal_txn(self, t: Transaction, cid: str, pg: PGState,
                      version: int) -> None:
        """Seal an empty log window at `version` (backfill completion)."""
        old_keys = [PGLog.omap_key(e.version) for e in pg.log.entries]
        pg.log.reset_to(version)
        pg.version = version
        t.touch(cid, pg.meta_oid())
        t.omap_setkeys(cid, pg.meta_oid(), {
            "head": str(version).encode(),
            "tail": str(version).encode(),
        })
        if old_keys:
            t.omap_rmkeys(cid, pg.meta_oid(), old_keys)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        # "osd.dispatch" (legacy: osd_debug_inject_dispatch_delay routed
        # as delay(sec)) — a delay action stalls this OSD's message
        # handling, the slow-daemon injection; an error action poisons
        # the message like a dispatcher bug would.  configured() guard:
        # this is the hottest dispatch path — stay free when off
        if fp_registry().configured("osd.dispatch"):
            failpoint("osd.dispatch", cct=self.cct, entity=self.whoami,
                      msg=type(msg).__name__)
        if isinstance(msg, MOSDOp):
            if TRACER.enabled and msg.trace_id is not None:
                # arrival stamp: _handle_client_op turns it into the
                # mClock dispatch-queue span (same trace_now clock)
                msg._rx_ts = trace_now()
            src = getattr(msg, "src", None)
            if src is not None:
                # notify fan-out reaches a watcher over the SAME
                # connection its ops arrive on (reference: the watch's
                # Session connection).  Bounded: oldest client entries
                # are dropped (their watches re-linger on the next map)
                self._client_conns.pop(src, None)
                self._client_conns[src] = conn  # re-insert: LRU position
                if len(self._client_conns) > 512:
                    self._client_conns.pop(
                        next(iter(self._client_conns)), None)
            # client ops flow through the mClock queue (reference:
            # OSD::ms_fast_dispatch -> op_shardedwq enqueue), under a
            # per-(client,pool) dynamic class when cephqos is armed —
            # the SAME identity the accounting table keys on, so the
            # controller's retuned params land on the tenants its
            # telemetry named (docs/qos.md)
            qcls = "client"
            if (self._qos_classes and src is not None
                    and not src.startswith("osd.")):
                # osd.* sources are internal forwards (split migration,
                # clone staging): they stay on the exempt static class
                # so a slot-full OSD can never deadlock a peer's op
                qcls = self.scheduler.client_class(f"{src}/{msg.pool}")
            self.scheduler.enqueue(
                qcls, lambda: self._handle_client_op(conn, msg)
            )
            return True
        if isinstance(msg, MQoSSettings):
            self._handle_qos_settings(msg)
            return True
        if isinstance(msg, MWatchNotifyAck):
            with self._watch_cond:
                self._notify_acks[(msg.notify_id, msg.cookie)] = True
                # bound the ack ledger (ids are monotonic; stale ones
                # are dead after their notify's timeout)
                while len(self._notify_acks) > 4096:
                    self._notify_acks.pop(next(iter(self._notify_acks)))
                self._watch_cond.notify_all()
            return True
        if isinstance(msg, MECSubOpWrite):
            self._handle_sub_write(conn, msg)
            return True
        if isinstance(msg, MECSubOpRead):
            self._handle_sub_read(conn, msg)
            return True
        if isinstance(msg, MPGPull):
            self._handle_pg_pull(conn, msg)
            return True
        if isinstance(
            msg,
            (MECSubOpWriteReply, MECSubOpReadReply, MPGNotify,
             MScrubShardReply, MOSDOpReply, MPGPullReply),
        ):
            # MOSDOpReply arrives when this OSD acts as its own client
            # (split migration forwarding ops to the post-split primary)
            with self._lock:
                if getattr(msg, "sender", None) is not None:
                    self._peer_load[int(msg.sender)] = (
                        time.monotonic(), int(msg.qlen or 0),
                        bool(msg.degraded))
                self._sub_replies[msg.tid] = msg
                # reap abandoned stragglers (wave replies past their
                # shared deadline — _wait_replies leaves them here).
                # tids are monotonic: evicting the oldest quarter only
                # bites a live waiter if its reply sat unclaimed while
                # 4096 newer ones arrived, far beyond any wave size
                if len(self._sub_replies) > 4096:
                    for tid in sorted(self._sub_replies)[:1024]:
                        del self._sub_replies[tid]
                self._cond.notify_all()
            return True
        if isinstance(msg, MPGQuery):
            self._handle_pg_query(conn, msg)
            return True
        if isinstance(msg, MPGClean):
            self._handle_pg_clean(msg)
            return True
        if isinstance(msg, MScrubShard):
            self._handle_scrub_shard(conn, msg)
            return True
        if isinstance(msg, MOSDPingMsg):
            if msg.op == "ping":
                try:
                    conn.send_message(
                        MOSDPingMsg(op="reply", osd=self.id, epoch=self.my_epoch())
                    )
                except (OSError, ConnectionError):
                    pass
            elif msg.op == "reply":
                self._hb_failures.pop(msg.osd, None)
                if msg.osd in self._hb_reported:
                    # we told the mon this peer was down and it just
                    # answered a ping: retract the report so the
                    # leader's corroboration count drains (reference:
                    # OSD::send_still_alive) instead of riding until
                    # the target re-boots.  Off-thread: report_alive
                    # may have to re-dial the mon, and this runs on the
                    # messenger rx thread, which must never block on a
                    # connect (the PR-4 ensure_connection rule)
                    self._hb_reported.discard(msg.osd)
                    threading.Thread(  # noqa: CL13 — fire-and-forget by design: report_alive must leave the messenger rx thread (no blocking dial there) and makes one bounded send
                        target=self.mc.report_alive, args=(msg.osd,),
                        name=f"osd.{self.id}-alive", daemon=True,
                    ).start()
            return True
        return False

    def _handle_qos_settings(self, msg: MQoSSettings) -> None:
        """Apply one controller push (mgr/qos_module.py): runtime
        options go through the SAME validate-all-then-apply core as
        injectargs; per-class (reservation, weight, limit) land on the
        scheduler.  Epoch-guarded — a stale push (reordered frames, a
        deposed mgr's last tick) must not roll settings back.  The
        background classes' floors are never controller-writable."""
        epoch = int(msg.qos_epoch or 0)
        with self._lock:
            if epoch <= self._qos_epoch:
                return
            self._qos_epoch = epoch
        applied: dict = {}
        try:
            if msg.options:
                from ..common.failpoint import apply_runtime_options

                applied = apply_runtime_options(
                    self.cct, sorted(msg.options.items()))
        except Exception as e:
            self.cct.dout("osd", 1,
                          f"{self.whoami} qos push epoch {epoch} options "
                          f"rejected: {e!r}")
            TRACER.tracepoint("qos", "reject", entity=self.whoami,
                              qos_epoch=epoch, error=repr(e))
            return
        n_classes = 0
        for name, rwl in sorted((msg.classes or {}).items()):
            if name in ("background_recovery", "background_scrub"):
                continue  # background floors are not controller-writable
            try:
                r, w, li = (float(rwl[0]), float(rwl[1]), float(rwl[2]))
                # register=False: the controller fans one cluster-wide
                # class map to every OSD — identities this OSD never
                # serves must not LRU-thrash its live classes; a class
                # that appears later starts on defaults and picks up
                # the params at the next push (one controller tick)
                if self.scheduler.set_params(
                        name, QoSParams(reservation=r, weight=w, limit=li),
                        register=False):
                    n_classes += 1
            except (ValueError, TypeError, IndexError) as e:
                self.cct.dout("osd", 1,
                              f"{self.whoami} qos class {name!r} params "
                              f"{rwl!r} rejected: {e!r}")
        TRACER.tracepoint("qos", "apply", entity=self.whoami,
                          qos_epoch=epoch, options=applied,
                          classes=n_classes)

    def _wait_reply(self, tid: int, timeout: float | None = None):
        # stop-aware: shutdown notifies _cond after setting _stop, so a
        # worker blocked here (recovery pulls, sub-writes) fails fast
        # instead of burning the full sub-op timeout under join
        if timeout is None:
            timeout = float(self.cct.conf.get("osd_subop_reply_timeout"))
        with self._lock:
            self._cond.wait_for(
                lambda: tid in self._sub_replies or self._stop.is_set(),
                timeout=timeout,
            )
            return self._sub_replies.pop(tid, None)

    def _wait_replies(self, tids, deadline: float) -> dict:
        """Collect replies for MANY tids under one SHARED deadline
        (advisor r4: N sequential per-reply waits made degraded-read
        stray probing O(N * timeout); a wave is bounded by the single
        deadline).  Returns {tid: reply} for those that arrived; late
        stragglers stay in _sub_replies for the reaper."""
        out: dict = {}
        pending = set(tids)
        with self._lock:
            while pending:
                for tid in [t for t in pending if t in self._sub_replies]:
                    out[tid] = self._sub_replies.pop(tid)
                    pending.discard(tid)
                if not pending or self._stop.is_set():
                    break  # shutdown fails the wave now, not at deadline
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    # timed out: drain anything that landed, then stop
                    for tid in [t for t in pending
                                if t in self._sub_replies]:
                        out[tid] = self._sub_replies.pop(tid)
                    break
        return out

    # -- heartbeats + recovery tick ---------------------------------------
    def _tick_loop(self) -> None:
        interval = 1.0
        last_hb = 0.0
        last_mgr = 0.0
        while not self._stop.is_set():
            self._recovery_wakeup.wait(timeout=interval)
            self._recovery_wakeup.clear()
            if self._stop.is_set():
                return
            now = time.monotonic()
            try:
                hb_interval = float(
                    self.cct.conf.get("osd_heartbeat_interval"))
                if now - last_hb >= hb_interval:
                    last_hb = now
                    self._heartbeat()
                self._check_op_workers(now)
                # keep the mon subscription alive: a crashed mon would
                # otherwise leave this OSD on a stale map forever (the
                # push-based subscription has no other liveness probe);
                # non-blocking — the hunt runs on a MonClient helper
                # thread so heartbeat cadence never stalls behind it
                self.mc.ensure_connection()
                if now - last_mgr >= self.cct.conf.get("mgr_report_interval"):
                    last_mgr = now
                    self._mgr_report()
                # recovery rides the mClock queue as background work so
                # client ops keep their reservation during big recoveries.
                # test-and-set under the daemon lock: the worker's reset
                # races an unlocked check (cephrace CR1), and a lost
                # update here double-books the single recovery slot
                with self._lock:
                    start_recovery = not self._recovery_inflight
                    if start_recovery:
                        self._recovery_inflight = True
                    start_split = not self._split_inflight
                    if start_split:
                        self._split_inflight = True
                if start_recovery:
                    self.scheduler.enqueue(
                        "background_recovery", self._recover_all_work
                    )
                if start_split:
                    self.scheduler.enqueue(
                        "background_recovery", self._split_pass_work
                    )
                self._maybe_schedule_scrub(now)
            except Exception as e:
                self.cct.dout("osd", 0, f"{self.whoami} tick failed: {e!r}")

    def _recover_all_work(self) -> None:
        try:
            self._recover_all()
        finally:
            with self._lock:
                self._recovery_inflight = False

